"""RMSE with sliding window (reference ``functional/image/rmse_sw.py``)."""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.helper import _check_image_shape, _uniform_filter

Array = jax.Array


def _rmse_sw_update(
    preds: Array,
    target: Array,
    window_size: int,
    rmse_val_sum: Optional[Array],
    rmse_map: Optional[Array],
    total_images: Optional[Array],
) -> Tuple[Array, Array, Array]:
    """Accumulate windowed RMSE sums (reference ``rmse_sw.py:10-74``)."""
    if preds.dtype != target.dtype:
        raise TypeError(
            f"Expected `preds` and `target` to have the same data type. But got {preds.dtype} and {target.dtype}."
        )
    _check_image_shape(preds, target)
    if round(window_size / 2) >= target.shape[2] or round(window_size / 2) >= target.shape[3]:
        raise ValueError(
            f"Parameter `round(window_size / 2)` is expected to be smaller than"
            f" {min(target.shape[2], target.shape[3])} but got {round(window_size / 2)}."
        )

    total_images = (total_images if total_images is not None else 0) + target.shape[0]
    error = (target - preds) ** 2
    error = _uniform_filter(error, window_size)
    _rmse_map = jnp.sqrt(error)
    crop_slide = round(window_size / 2)

    rmse_val = _rmse_map[:, :, crop_slide:-crop_slide, crop_slide:-crop_slide].sum(0).mean()
    rmse_val_sum = (rmse_val_sum if rmse_val_sum is not None else 0.0) + rmse_val
    rmse_map = (rmse_map if rmse_map is not None else 0.0) + _rmse_map.sum(0)
    return rmse_val_sum, rmse_map, jnp.asarray(total_images)


def _rmse_sw_compute(
    rmse_val_sum: Optional[Array], rmse_map: Array, total_images: Array
) -> Tuple[Optional[Array], Array]:
    """Normalize accumulated sums (reference ``rmse_sw.py:77-93``)."""
    rmse = rmse_val_sum / total_images if rmse_val_sum is not None else None
    rmse_map = rmse_map / total_images if rmse_map is not None else None
    return rmse, rmse_map


def root_mean_squared_error_using_sliding_window(
    preds: Array, target: Array, window_size: int = 8, return_rmse_map: bool = False
) -> Union[Optional[Array], Tuple[Optional[Array], Array]]:
    """Windowed RMSE (reference ``rmse_sw.py:96-131``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.functional.image.rmse_sw import root_mean_squared_error_using_sliding_window
        >>> print(round(float(root_mean_squared_error_using_sliding_window(preds, target)), 4))
        0.0763
    """
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    rmse_val_sum, rmse_map, total_images = _rmse_sw_update(
        preds, target, window_size, rmse_val_sum=None, rmse_map=None, total_images=None
    )
    rmse, rmse_map = _rmse_sw_compute(rmse_val_sum, rmse_map, total_images)
    if return_rmse_map:
        return rmse, rmse_map
    return rmse
