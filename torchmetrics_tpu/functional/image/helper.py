"""Shared filter kernels for image metrics (reference ``functional/image/helper.py``).

TPU-first: every separable window filter (gaussian, uniform) is applied as dense
band-matrix **einsum matmuls** over the H and W axes instead of ``lax.conv``. A 1-D
k-tap filter along an axis of length n is exactly ``Y = M·X`` with a banded
(n−k+1, n) matrix M — a plain matmul that rides the MXU. Depthwise convolutions never
map to the MXU at all (and measure ~3500× slower than the equivalent matmul on this
TPU), so the filters here contain no conv calls; the band matrices depend only on
static shapes and are built in numpy, becoming XLA constants under jit. The reference
instead loops channels through ``F.conv2d`` (``helper.py:115-131``).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _gaussian_np(kernel_size: int, sigma: float) -> np.ndarray:
    """1D gaussian window as a host constant, normalized to sum 1."""
    dist = np.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, dtype=np.float64)
    gauss = np.exp(-((dist / sigma) ** 2) / 2)
    return gauss / gauss.sum()


def _band_matrix_np(kernel: np.ndarray, n_in: int) -> np.ndarray:
    """(n_out, n_in) banded matrix applying a VALID 1D correlation with ``kernel``."""
    k = kernel.shape[0]
    n_out = n_in - k + 1
    m = np.zeros((n_out, n_in), dtype=np.float64)
    rows = np.arange(n_out)
    for i in range(k):
        m[rows, rows + i] = kernel[i]
    return m


def _filter_separable_2d(x: Array, kernel_h: np.ndarray, kernel_w: np.ndarray) -> Array:
    """VALID separable filter over NCHW via two band-matrix matmuls (MXU path)."""
    mh = jnp.asarray(_band_matrix_np(kernel_h, x.shape[2]), dtype=x.dtype)
    mw = jnp.asarray(_band_matrix_np(kernel_w, x.shape[3]), dtype=x.dtype)
    y = jnp.einsum("oh,nchw->ncow", mh, x)
    return jnp.einsum("pw,ncow->ncop", mw, y)


def _filter_separable_3d(x: Array, k_d: np.ndarray, k_h: np.ndarray, k_w: np.ndarray) -> Array:
    """VALID separable filter over NCDHW via three band-matrix matmuls."""
    md = jnp.asarray(_band_matrix_np(k_d, x.shape[2]), dtype=x.dtype)
    mh = jnp.asarray(_band_matrix_np(k_h, x.shape[3]), dtype=x.dtype)
    mw = jnp.asarray(_band_matrix_np(k_w, x.shape[4]), dtype=x.dtype)
    y = jnp.einsum("od,ncdhw->ncohw", md, x)
    y = jnp.einsum("ph,ncdhw->ncdpw", mh, y)
    return jnp.einsum("qw,ncdhw->ncdhq", mw, y)


def _gaussian(kernel_size: int, sigma: float, dtype: jnp.dtype) -> Array:
    """1D gaussian window, normalized to sum 1 (reference ``helper.py:11-26``)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, dtype=dtype)
    gauss = jnp.exp(-((dist / sigma) ** 2) / 2)
    return (gauss / gauss.sum())[None, :]  # (1, kernel_size)


def _gaussian_kernel_2d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype: jnp.dtype
) -> Array:
    """(C,1,kh,kw) depthwise gaussian kernel (reference ``helper.py:29-58``)."""
    kx = _gaussian(kernel_size[0], sigma[0], dtype)
    ky = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = kx.T @ ky  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _gaussian_kernel_3d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype: jnp.dtype
) -> Array:
    """(C,1,kd,kh,kw)-style depthwise 3D gaussian kernel (reference ``helper.py:135-152``)."""
    kx = _gaussian(kernel_size[0], sigma[0], dtype)
    ky = _gaussian(kernel_size[1], sigma[1], dtype)
    kz = _gaussian(kernel_size[2], sigma[2], dtype)
    kernel_xy = kx.T @ ky  # (kh, kw)
    kernel = kernel_xy[:, :, None] * kz[0][None, None, :]
    return jnp.broadcast_to(kernel, (channel, 1, *kernel.shape))


def _avg_pool2d(x: Array) -> Array:
    """2×2/stride-2 average pool, NCHW, as crop + reshape-mean (no reduce_window).

    Equivalent to torch ``F.avg_pool2d(x, (2, 2))``: VALID windows floor odd dims.
    """
    n, c, h, w = x.shape
    x = x[..., : h // 2 * 2, : w // 2 * 2]
    return x.reshape(n, c, h // 2, 2, w // 2, 2).mean(axis=(3, 5))


def _avg_pool3d(x: Array) -> Array:
    """2×2×2/stride-2 average pool, NCDHW, as crop + reshape-mean."""
    n, c, d, h, w = x.shape
    x = x[..., : d // 2 * 2, : h // 2 * 2, : w // 2 * 2]
    return x.reshape(n, c, d // 2, 2, h // 2, 2, w // 2, 2).mean(axis=(3, 5, 7))


def _reflect_pad_2d(x: Array, pad_h: int, pad_w: int) -> Array:
    """Reflection pad H/W of an NCHW tensor (edge not repeated — torch 'reflect')."""
    return jnp.pad(x, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _reflect_pad_3d(x: Array, pad_d: int, pad_h: int, pad_w: int) -> Array:
    """Reflection pad D/H/W of an NCDHW tensor."""
    return jnp.pad(x, ((0, 0), (0, 0), (pad_d, pad_d), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _single_dimension_pad(x: Array, dim: int, pad: int, outer_pad: int = 0) -> Array:
    """Scipy-style asymmetric reflection pad over one dim (reference ``helper.py:78-94``).

    Left gets ``pad`` mirrored rows, right gets ``pad + outer_pad - 1`` — the layout
    scipy's ``uniform_filter`` uses for even windows.
    """
    n = x.shape[dim]
    left = jax.lax.rev(jax.lax.slice_in_dim(x, 0, pad, axis=dim), (dim,))
    right = jax.lax.rev(jax.lax.slice_in_dim(x, n - pad - outer_pad + 1, n, axis=dim), (dim,))
    return jnp.concatenate([left, x, right], axis=dim)


def _uniform_filter(x: Array, window_size: int) -> Array:
    """Scipy-compatible uniform filter over an NCHW tensor (reference ``helper.py:112-131``).

    The k×k mean window is separable ((1/k)⊗(1/k)), so it runs as two band matmuls.
    """
    for dim in (2, 3):
        x = _single_dimension_pad(x, dim, window_size // 2, outer_pad=window_size % 2)
    k1d = np.full(window_size, 1.0 / window_size)
    return _filter_separable_2d(x, k1d, k1d)


def _check_image_shape(preds: Array, target: Array, ndim: int = 4) -> Tuple[Array, Array]:
    """Common BxCxHxW validation used by the pixel metrics."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )
    if preds.ndim != ndim:
        raise ValueError(
            f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target
