"""CLIPScore functional (reference ``functional/multimodal/clip_score.py``).

The embedding backend is an injection point: pass ``model``/``processor`` callables (any
image/text towers returning embeddings) and the metric core — L2-normalize, cosine, x100
— runs in jnp. The default backend loads the HF ``CLIPModel`` like the reference
(``clip_score.py:24-96``), gated on ``transformers`` availability; the zero-download
injected path keeps the metric testable without weights.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.imports import _TRANSFORMERS_AVAILABLE

Array = jax.Array

_DEFAULT_MODEL = "openai/clip-vit-large-patch14"


def _get_model_and_processor(model_name_or_path: str = _DEFAULT_MODEL) -> Tuple[Any, Any]:
    """HF CLIP towers (reference ``clip_score.py:79-96``)."""
    if _TRANSFORMERS_AVAILABLE:
        from transformers import CLIPModel, CLIPProcessor

        try:
            return CLIPModel.from_pretrained(model_name_or_path), CLIPProcessor.from_pretrained(model_name_or_path)
        except Exception as exc:  # noqa: BLE001 — offline-clean error instead of hub traceback
            from torchmetrics_tpu.utilities.hf import _load_error

            raise _load_error(model_name_or_path, exc) from exc
    raise ModuleNotFoundError(
        "`clip_score` metric requires `transformers` package be installed."
        " Either install with `pip install transformers>=4.0` or `pip install torchmetrics[multimodal]`."
    )


def _hf_embed(images: List[Array], text: List[str], model: Any, processor: Any) -> Tuple[Array, Array]:
    """Run the HF towers on host and return (img_features, txt_features) as jnp arrays."""
    import torch

    processed = processor(
        text=text, images=[np.asarray(i) for i in images], return_tensors="pt", padding=True
    )
    with torch.no_grad():
        img_features = model.get_image_features(processed["pixel_values"]).numpy()
        txt_features = model.get_text_features(processed["input_ids"], processed["attention_mask"]).numpy()
    return jnp.asarray(img_features), jnp.asarray(txt_features)


def _clip_score_update(
    images: Union[Array, List[Array]],
    text: Union[str, List[str]],
    model: Any,
    processor: Any,
    embed_fn: Optional[Callable[[List[Array], List[str]], Tuple[Array, Array]]] = None,
) -> Tuple[Array, int]:
    """Per-pair 100 x cosine similarity (reference ``clip_score.py:41-76``)."""
    if not isinstance(images, list):
        images = [images] if images.ndim == 3 else list(images)
    else:
        images = list(images)
    if not all(i.ndim == 3 for i in images):
        raise ValueError("Expected all images to be 3d but found image that has either more or less")
    if not isinstance(text, list):
        text = [text]
    if len(text) != len(images):
        raise ValueError(
            f"Expected the number of images and text examples to be the same but got {len(images)} and {len(text)}"
        )

    if embed_fn is not None:
        img_features, txt_features = embed_fn(images, text)
    else:
        img_features, txt_features = _hf_embed(images, text, model, processor)

    img_features = img_features / jnp.linalg.norm(img_features, axis=-1, keepdims=True)
    txt_features = txt_features / jnp.linalg.norm(txt_features, axis=-1, keepdims=True)
    score = 100 * (img_features * txt_features).sum(axis=-1)
    return score, len(text)


def clip_score(
    images: Union[Array, List[Array]],
    text: Union[str, List[str]],
    model_name_or_path: str = _DEFAULT_MODEL,
    embed_fn: Optional[Callable[[List[Array], List[str]], Tuple[Array, Array]]] = None,
) -> Array:
    r"""CLIPScore(I, C) = max(100 * cos(E_I, E_C), 0) averaged over pairs (reference ``clip_score.py:99-151``)."""
    if embed_fn is None:
        model, processor = _get_model_and_processor(model_name_or_path)
    else:
        model = processor = None
    score, _ = _clip_score_update(images, text, model, processor, embed_fn)
    score = score.mean(0)
    return jnp.maximum(score, jnp.zeros_like(score))
