"""In-graph streaming aggregation: windowed rings and exponential decay.

``wrappers/running.py`` keeps a trailing window by snapshotting the FULL base
state once per update on the host path — O(window) state copies, O(window)
Python attribute traffic per step, and ``compute`` replays a host-side
merge per slot. For a serving loop over an unbounded stream that is the wrong
shape entirely. This module re-expresses the same semantics device-first:

- :class:`WindowedMetric` — a fixed ring of ``buckets`` partial states, each
  covering ``bucket_size`` updates. Advance (ring cursor), evict (reset the
  re-entered slot to its default) and fold (batch contribution into the
  cursor slot) all lower into ONE donated engine dispatch per step; memory is
  ``buckets ×`` the base state, independent of stream length.
- :class:`DecayedMetric` — exponential time-decay (EMA) states: additive base
  states accumulate as ``state = decay * state + contribution``, so the
  effective window is ``1 / (1 - decay)`` updates with O(1) state.

Both wrappers hold their base metric purely as a TRACED BODY: the batch
contribution comes from running the base's raw update on default states with
the engine's own snapshot/restore hygiene (``traced_update``), never from the
base's live host machinery — which is why they may declare
the traced-body attribute in ``_engine_traced_bodies`` and compile despite
owning an inner Metric.
Ring/EMA states are ordinary registered states with standard reductions, so
the packed epoch sync (``parallel/packing.py``) moves them with zero new
collective roles and — all shapes being fixed — zero metadata gathers.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.engine.compiled import _Ineligible, traced_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_max, dim_zero_min, dim_zero_sum
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

Array = jax.Array

__all__ = ["DecayedMetric", "WindowedMetric"]

#: reductions a streaming wrapper can fold per-slot / per-tick: each is an
#: associative merge whose identity element is the registered default
_FOLDS = {
    dim_zero_sum: ("sum", jnp.add),
    dim_zero_max: ("max", jnp.maximum),
    dim_zero_min: ("min", jnp.minimum),
}


# tmlint: boundary(serve-setup) — one-time construction-path validation; the
# default-value reads below ride the serve-setup boundary (never the hot loop)
def check_streamable(base: Metric, wrapper: str) -> Dict[str, Tuple[str, Any]]:
    """Validate a base metric for streaming wrappers; returns attr -> fold.

    Eligible: fixed-shape array states whose reduction is sum/max/min and —
    for sum — whose default is the additive identity (all-zero). Mean-reduced
    states are rejected with a pointer at the sum/count formulation
    (``MeanMetric`` already uses it); list/cat/None/custom states have no
    slot-merge algebra.
    """
    import numpy as np

    if not isinstance(base, Metric):
        raise TorchMetricsUserError(
            f"Expected the base metric to be a `torchmetrics_tpu.Metric` but got {base!r}"
        )
    folds: Dict[str, Tuple[str, Any]] = {}
    for attr, red in base._reductions.items():
        default = base._defaults[attr]
        if isinstance(default, list):
            raise TorchMetricsUserError(
                f"{wrapper} cannot stream metric {type(base).__name__!r}: list state"
                f" {attr!r} grows unboundedly — a fixed-memory window cannot hold it."
            )
        fold = _FOLDS.get(red)
        if fold is None:
            hint = (
                " (mean-reduced states have no per-slot identity; use a sum/count"
                " formulation like MeanMetric's instead)"
                if red is not None and getattr(red, "__name__", "") == "dim_zero_mean"
                else ""
            )
            raise TorchMetricsUserError(
                f"{wrapper} cannot stream metric {type(base).__name__!r}: state {attr!r}"
                f" has an unsupported reduction{hint}; only sum/max/min states fold"
                " into ring slots."
            )
        from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

        # one-time construction read of the registered default (the sentinel's
        # "sentinel-setup" precedent) — never on the update path
        with transfer_allowed("serve-setup"):
            nonzero_default = bool(np.asarray(default).any())
        if fold[0] == "sum" and nonzero_default:
            raise TorchMetricsUserError(
                f"{wrapper} cannot stream metric {type(base).__name__!r}: sum-reduced"
                f" state {attr!r} has a non-zero default, so the default is not the"
                " fold identity an evicted slot resets to."
            )
        if fold[0] in ("max", "min") and np.issubdtype(np.asarray(default).dtype, np.floating):
            # never-written / evicted slots hold the default, and the
            # across-slot fold treats them as transparent ONLY if the default
            # is the fold identity (−inf for max, +inf for min — what
            # Max/MinMetric register). A 0-default max state over an
            # all-negative stream would silently report 0. Integer extremum
            # states are exempt: their identity is domain-dependent (e.g. 0
            # is correct for non-negative rank registers) — documented.
            identity = -np.inf if fold[0] == "max" else np.inf
            with transfer_allowed("serve-setup"):
                is_identity = bool((np.asarray(default) == identity).all())
            if not is_identity:
                raise TorchMetricsUserError(
                    f"{wrapper} cannot stream metric {type(base).__name__!r}:"
                    f" {fold[0]}-reduced float state {attr!r} has default"
                    f" {np.asarray(default)!r}, not the fold identity"
                    f" ({identity}) an evicted slot resets to."
                )
        folds[attr] = fold
    return folds


def capture_np_defaults(base: Metric, keys: Tuple[str, ...]) -> Dict[str, Any]:
    """Numpy copies of the base defaults, captured ONCE under the sanctioned
    boundary: referencing a live jax array inside a traced body embeds it as a
    graph constant, and materializing that constant reads the device buffer —
    which the strict transfer guard correctly flags. A numpy-backed constant
    is host data and trips nothing. Shared by every traced-body wrapper
    (windows, decay, tenancy) so the hygiene cannot drift between them.
    """
    import numpy as np

    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    with transfer_allowed("serve-setup"):
        return {k: np.asarray(base._defaults[k]) for k in keys}


def extract_contribution(
    base: Metric,
    np_defaults: Dict[str, Any],
    keys: Tuple[str, ...],
    wrapper: str,
    args: Tuple[Any, ...],
    kwargs: Dict[str, Any],
) -> Dict[str, Any]:
    """The batch's pure contribution: base raw update on default states.

    Runs under :func:`traced_update` snapshot/restore hygiene; eagerly
    (outside an engine trace) a side-effectful base body is a hard semantic
    error, not a fallback.
    """
    defaults = {k: jnp.asarray(np_defaults[k]) for k in keys}
    try:
        return traced_update(base, defaults, args, kwargs)
    except _Ineligible as exc:
        raise TorchMetricsUserError(
            f"{wrapper} cannot stream {type(base).__name__!r}: {exc}"
        ) from exc


def run_base_compute(base: Metric, states: Dict[str, Any]) -> Any:
    """Run the base's raw compute body on the given state values, hygienically.

    The base's ``__dict__`` is snapshotted and restored wholesale (the
    ``traced_update`` discipline), so neither a host call nor a trace can leak
    values onto the live object. ``_update_count`` is pinned to 1: the window
    has folded real updates into these states, and raw compute bodies only
    ever read the count through mean weighting, which sum/count-style bases do
    via their own states.
    """
    snapshot = dict(base.__dict__)
    try:
        for key, value in states.items():
            object.__setattr__(base, key, value)
        object.__setattr__(base, "_update_count", 1)
        return base._raw_compute()
    finally:
        base.__dict__.clear()
        base.__dict__.update(snapshot)


class _StreamingWrapper(Metric):
    """Shared base: contribution extraction + base-compute plumbing."""

    #: engine/compiled.py eligibility exemption — ATTRIBUTE-scoped: only the
    #: named inner metric is used as a traced body under snapshot/restore
    #: hygiene; any other nested metric still disqualifies compilation
    _engine_traced_bodies = frozenset({"base_metric"})
    #: forward must use the safe two-update path: the reduce path's
    #: reset+merge would misalign the ring cursor / decay tick
    full_state_update = True

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._slot_folds = check_streamable(base_metric, type(self).__name__)
        self.base_metric = base_metric
        self._base_keys = tuple(base_metric._defaults)
        self._np_defaults = capture_np_defaults(base_metric, self._base_keys)

    def _default_of(self, key: str) -> Any:
        """The base state's default as a trace-safe (numpy-backed) constant."""
        return jnp.asarray(self._np_defaults[key])

    def _contribution(self, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Dict[str, Any]:
        """The batch's pure contribution: base raw update on default states."""
        return extract_contribution(
            self.base_metric, self._np_defaults, self._base_keys,
            type(self).__name__, args, kwargs,
        )

    def plot(
        self, val: Optional[Union[Array, Sequence[Array]]] = None, ax: Optional[Any] = None
    ) -> Any:
        return self._plot(val, ax)


class WindowedMetric(_StreamingWrapper):
    """Trailing-window metric over a fixed ring of partial states.

    The window covers the last ``buckets * bucket_size`` updates at
    ``bucket_size``-update granularity: each ring slot accumulates
    ``bucket_size`` consecutive updates, and re-entering a slot after a full
    revolution evicts it (resets to the registered default) in the same
    graph. ``compute()`` folds all slots with the base reduction — evicted
    and never-written slots hold the fold identity, so no occupancy mask is
    needed — and runs the base's compute body on the folded state.

    Unlike :class:`~torchmetrics_tpu.wrappers.running.Running` (O(window)
    host-side state snapshots per update, exact per-update granularity), the
    ring is O(buckets) device memory with advance/evict/fold compiled into
    one donated dispatch per step.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SumMetric
        >>> from torchmetrics_tpu.serve import WindowedMetric
        >>> metric = WindowedMetric(SumMetric(nan_strategy=0.0), buckets=3, bucket_size=1)
        >>> for v in (1.0, 2.0, 3.0, 4.0):
        ...     metric.update(jnp.asarray(v))
        >>> float(metric.compute())  # sum over the trailing window {2, 3, 4}
        9.0
    """

    def __init__(self, base_metric: Metric, buckets: int = 8, bucket_size: int = 1, **kwargs: Any) -> None:
        super().__init__(base_metric, **kwargs)
        if not (isinstance(buckets, int) and buckets > 0):
            raise ValueError(f"Expected argument `buckets` to be a positive int but got {buckets}")
        if not (isinstance(bucket_size, int) and bucket_size > 0):
            raise ValueError(f"Expected argument `bucket_size` to be a positive int but got {bucket_size}")
        self.buckets = buckets
        self.bucket_size = bucket_size
        for key in self._base_keys:
            default = base_metric._defaults[key]
            ring_default = jnp.broadcast_to(default, (buckets,) + tuple(default.shape))
            # slot-merge algebra == cross-rank algebra: per-slot partials fold
            # elementwise across ranks with the base state's own reduction
            self.add_state("win_" + key, default=ring_default, dist_reduce_fx=base_metric._reductions[key])
        # lockstep tick counter; max-reduced so a cross-rank sync cannot
        # double-count the shared clock. Dtype rides the PR-8 count contract
        # (engine/numerics.count_dtype: int64 under x64, resolved at creation)
        # — an unbounded serving stream must not wrap its clock at 2**31.
        from torchmetrics_tpu.engine.numerics import count_dtype

        self.add_state(
            "clock", default=jnp.zeros((), count_dtype()), dist_reduce_fx="max",
            spec={"role": "ring-clock", "dtype_policy": "count"},
        )

    def update(self, *args: Any, **kwargs: Any) -> None:
        """One stream tick: contribution + advance/evict/fold, one graph."""
        contrib = self._contribution(args, kwargs)
        clock = self.clock
        cursor = (clock // self.bucket_size) % self.buckets
        entering = (clock % self.bucket_size) == 0
        for key in self._base_keys:
            ring = getattr(self, "win_" + key)
            # evict-on-entry: the slot re-entered after a full revolution
            # restarts from the registered default (the fold identity)
            slot = jnp.where(entering, self._default_of(key), ring[cursor])
            merged = self._slot_folds[key][1](slot, contrib[key])
            setattr(self, "win_" + key, ring.at[cursor].set(merged))
        self.clock = clock + jnp.asarray(1, clock.dtype)

    def compute(self) -> Any:
        """Fold the ring across slots and run the base compute on the result."""
        across = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}
        folded = {
            key: across[self._slot_folds[key][0]](getattr(self, "win_" + key), axis=0)
            for key in self._base_keys
        }
        return run_base_compute(self.base_metric, folded)


class DecayedMetric(_StreamingWrapper):
    """Exponentially time-decayed metric states (EMA over the update stream).

    Additive (sum-reduced) base states accumulate as
    ``state = decay * state + contribution`` per update; max/min states fold
    undecayed (a decayed extremum has no meaning). A sum/count base like
    ``MeanMetric`` therefore yields a genuine EMA mean — numerator and
    denominator decay together. The effective window is ``1 / (1 - decay)``
    updates; pass ``half_life`` to derive ``decay = 0.5 ** (1 / half_life)``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SumMetric
        >>> from torchmetrics_tpu.serve import DecayedMetric
        >>> metric = DecayedMetric(SumMetric(nan_strategy=0.0), decay=0.5)
        >>> for v in (4.0, 2.0, 1.0):
        ...     metric.update(jnp.asarray(v))
        >>> float(metric.compute())  # 4*0.25 + 2*0.5 + 1
        3.0
    """

    def __init__(
        self,
        base_metric: Metric,
        decay: Optional[float] = None,
        half_life: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(base_metric, **kwargs)
        if (decay is None) == (half_life is None):
            raise ValueError("Provide exactly one of `decay` or `half_life`")
        if half_life is not None:
            if not (isinstance(half_life, int) and half_life > 0):
                raise ValueError(f"Expected argument `half_life` to be a positive int but got {half_life}")
            decay = 0.5 ** (1.0 / half_life)
        if not (isinstance(decay, float) and 0.0 < decay < 1.0):
            raise ValueError(f"Expected argument `decay` to be a float in (0, 1) but got {decay}")
        self.decay = decay
        for key in self._base_keys:
            self.add_state(
                "ema_" + key,
                default=base_metric._defaults[key],
                dist_reduce_fx=base_metric._reductions[key],
            )

    def update(self, *args: Any, **kwargs: Any) -> None:
        """One stream tick: decay additive states, fold the contribution in."""
        contrib = self._contribution(args, kwargs)
        for key in self._base_keys:
            kind, fold = self._slot_folds[key]
            state = getattr(self, "ema_" + key)
            if kind == "sum":
                state = state * jnp.asarray(self.decay, state.dtype) + contrib[key]
            else:
                state = fold(state, contrib[key])
            setattr(self, "ema_" + key, state)

    def compute(self) -> Any:
        """Run the base compute on the decayed states."""
        return run_base_compute(
            self.base_metric, {key: getattr(self, "ema_" + key) for key in self._base_keys}
        )
