"""Pause-free snapshot-compute: scrape-anytime ``compute()`` off the hot loop.

A Prometheus scrape that calls ``metric.compute()`` on the live object would
sync, re-anchor, and potentially unsync mid-stream — pausing the hot loop and
racing its donation. This module makes scrapes a SHIELDED read instead:

1. :func:`take_snapshot` grabs the state refs at a consistent watermark
   (retrying around in-flight mutations via the ``_mutation_depth`` guard the
   PR-7 preemption snapshots introduced) and immediately re-materializes each
   leaf as a fresh device buffer (``jnp.array(copy=True)``). The copy is an
   ASYNC device dispatch — the update thread never blocks — and it is what
   donation-proofs the snapshot: the hot loop's next donated step consumes
   the OLD buffers, not the snapshot's.
2. :func:`snapshot_compute` runs the metric's raw compute body on a cached
   scratch clone holding the snapshot state — rank-local by design (a scrape
   reads THIS host's view; cross-rank totals belong to the epoch sync), so
   nothing synchronizes, nothing unsyncs, and the live metric's caches and
   counters are untouched.

The flight recorder narrates both halves (``serve.snapshot`` /
``serve.snapshot.read`` events, the read carrying ``updates_between`` — the
proof that updates kept landing while the snapshot computed).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from time import perf_counter
from typing import Any, Dict

import jax.numpy as jnp

from torchmetrics_tpu.diag import lineage as _lineage
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.serve import stats as _serve_stats
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = ["StateSnapshot", "read_host", "snapshot_compute", "take_snapshot"]

#: scratch clones per live metric — built once (deepcopy), reused per scrape.
#: Entries are ``id(metric) -> (weakref(metric), scratch)``: the weakref's
#: finalize callback evicts the entry when the source metric dies (so clones
#: holding device arrays cannot accumulate for the life of the process), and
#: the liveness check guards against id reuse in the window before the
#: callback runs.
_SCRATCH: Dict[int, Any] = {}  # guarded-by: _SCRATCH_LOCK
_SCRATCH_LOCK = threading.Lock()


@dataclass
class StateSnapshot:
    """A donation-proof copy of one metric's state at a known watermark."""

    state: Dict[str, Any]
    update_count: int
    retries: int = 0
    extras: Dict[str, Any] = field(default_factory=dict)
    #: what the snapshot covers (diag/lineage.py ``ValueProvenance.as_dict()``
    #: form); empty when the provenance plane is off
    provenance: Dict[str, Any] = field(default_factory=dict)


def _copy_leaf(value: Any) -> Any:
    if isinstance(value, list):
        return [jnp.array(v, copy=True) for v in value]
    return jnp.array(value, copy=True)


def take_snapshot(metric: Any) -> StateSnapshot:
    """Consistent, donation-proof state copy without pausing updates.

    Consistency protocol: grab refs only while no mutation is in flight
    (``_mutation_depth == 0``) and re-check the update watermark afterwards;
    a concurrent update (or a donated buffer consumed between grab and copy)
    retries, up to ``TORCHMETRICS_TPU_SERVE_SNAPSHOT_RETRIES`` attempts. The
    final attempt's copy failing is a real error — a scrape must never
    surface a torn state as a value.
    """
    import time

    from torchmetrics_tpu.engine.scan import flush_metric

    # flush-on-observation (engine/scan.py): a snapshot must hold every
    # enqueued step — a scrape can never see state K steps stale
    flush_metric(metric, "observation:snapshot")
    budget = _serve_stats.snapshot_retries()
    last_exc: Any = None
    for attempt in range(budget):
        if attempt:
            # yield the GIL so a concurrent mid-mutation update can actually
            # finish between attempts (a bare spin would burn the whole retry
            # budget inside one GIL slice), escalating to a short real sleep
            time.sleep(0 if attempt < 3 else 0.001 * attempt)
        if getattr(metric, "_mutation_depth", 0):
            continue  # an update is mid-write; retry after the yield above
        watermark = metric._update_count
        refs = {}
        for key in metric._defaults:
            value = getattr(metric, key)
            refs[key] = list(value) if isinstance(value, list) else value
        if metric._update_count != watermark or getattr(metric, "_mutation_depth", 0):
            continue  # the watermark moved under us — refs may be torn
        try:
            copies = {key: _copy_leaf(value) for key, value in refs.items()}
        except Exception as exc:  # noqa: BLE001 — a donated-away buffer between grab and copy
            last_exc = exc
            continue
        extras = {}
        quarantined = metric.__dict__.get("_quarantined_count")
        if quarantined is not None:
            extras["_quarantined_count"] = _copy_leaf(quarantined)
        residuals = metric.__dict__.get("_comp_residuals")
        if residuals:
            extras["_comp_residuals"] = {k: _copy_leaf(v) for k, v in residuals.items()}
        _diag.record(
            "serve.snapshot", type(metric).__name__,
            update_count=int(watermark), retries=attempt,
        )
        _serve_stats.note_snapshot(attempt)
        # the snapshot IS an observation: the queue flushed above, so the
        # record attests exactly what the copied state covers
        record = _lineage.observe_metric(metric, "snapshot")
        return StateSnapshot(
            state=copies, update_count=int(watermark), retries=attempt, extras=extras,
            provenance=record.as_dict() if record is not None else {},
        )
    raise TorchMetricsUserError(
        f"Could not take a consistent snapshot of {type(metric).__name__} within"
        f" {budget} attempts (TORCHMETRICS_TPU_SERVE_SNAPSHOT_RETRIES); the update"
        f" loop never quiesced between dispatches." + (f" Last error: {last_exc}" if last_exc else "")
    )


def read_host(metric: Any, attrs: Any, index: Any = None) -> Dict[str, Any]:
    """Scrape-path host read of named states with the snapshot retry discipline.

    The serving views (tenant tables, sketch registers) read LIVE buffers that
    a donated hot-loop dispatch may consume mid-read — the same race
    :func:`take_snapshot` arbitrates. This shares its protocol (mutation-depth
    gate, GIL yield between attempts, retry on a consumed buffer) for reads
    that only need a few numpy arrays, not a full donation-proof copy; the
    fetch itself rides the sanctioned ``serve-scrape`` boundary.

    ``index`` (optional) selects ``state[index]`` device-side before the
    transfer — a per-tenant view moves one row per state to host, not the
    whole capacity-sized table.
    """
    import time

    import numpy as np

    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed
    from torchmetrics_tpu.engine.scan import flush_metric

    # flush-on-observation (engine/scan.py): the scrape views (tenant tables,
    # sketch registers, ring clocks) must reflect every enqueued step
    flush_metric(metric, "observation:scrape")
    _lineage.observe_metric(metric, "scrape")
    attrs = tuple(attrs)
    budget = _serve_stats.snapshot_retries()
    last_exc: Any = None
    for attempt in range(budget):
        if attempt:
            time.sleep(0 if attempt < 3 else 0.001 * attempt)
        if getattr(metric, "_mutation_depth", 0):
            continue
        try:
            with transfer_allowed("serve-scrape"):
                if index is None:
                    return {a: np.asarray(getattr(metric, a)) for a in attrs}
                return {a: np.asarray(getattr(metric, a)[index]) for a in attrs}
        except Exception as exc:  # noqa: BLE001 — a donated-away buffer mid-read
            last_exc = exc
            continue
    raise TorchMetricsUserError(
        f"Could not read {attrs} from {type(metric).__name__} within {budget}"
        f" attempts (TORCHMETRICS_TPU_SERVE_SNAPSHOT_RETRIES)."
        + (f" Last error: {last_exc}" if last_exc else "")
    )


def _scratch_for(metric: Any) -> Any:
    """The cached compute-only clone for this metric instance (built once)."""
    import weakref

    key = id(metric)
    with _SCRATCH_LOCK:
        entry = _SCRATCH.get(key)
        if entry is None or entry[0]() is not metric:
            scratch = metric.clone()
            # scrape computes are rank-local reads: never sync, never cache
            scratch.sync_on_compute = False
            scratch._to_sync = False
            scratch.compute_with_cache = False

            def _evict(_ref: Any, _key: int = key) -> None:
                # lock-free on purpose: the callback can fire from GC at ANY
                # allocation — including inside the locked clone above, where
                # taking the (non-reentrant) lock again would deadlock.
                # dict.pop is GIL-atomic, which is all the atomicity needed.
                _SCRATCH.pop(_key, None)

            # the per-entry lock serializes CONCURRENT scrapes of one metric:
            # install/compute/restore on the shared scratch is a critical
            # section (two unlocked scrapes would interleave their state
            # installs and return each other's values)
            _SCRATCH[key] = entry = (weakref.ref(metric, _evict), scratch, threading.Lock())
    return entry


def snapshot_compute(metric: Any, snapshot: StateSnapshot = None) -> Any:
    """``compute()`` on a shielded copy while the live metric keeps updating.

    Returns the computed value for the snapshot's watermark. The live
    metric's state, caches (``_computed``), and sync status are untouched;
    between :func:`take_snapshot` and the value read the hot loop keeps
    dispatching — the ``serve.snapshot.read`` event records how many updates
    landed in that window.
    """
    if snapshot is None:
        snapshot = take_snapshot(metric)
    _ref, scratch, lock = _scratch_for(metric)
    t0 = perf_counter()
    with lock:
        prior = dict(scratch.__dict__)
        try:
            for key, value in snapshot.state.items():
                object.__setattr__(scratch, key, value)
            for key, value in snapshot.extras.items():
                object.__setattr__(scratch, key, value)
            object.__setattr__(scratch, "_update_count", max(snapshot.update_count, 1))
            object.__setattr__(scratch, "_computed", None)
            value = scratch._raw_compute()
        finally:
            scratch.__dict__.clear()
            scratch.__dict__.update(prior)
    span = snapshot.provenance.get("span") if snapshot.provenance else None
    _diag.record(
        "serve.snapshot.read", type(metric).__name__,
        update_count=snapshot.update_count,
        updates_between=int(metric._update_count) - snapshot.update_count,
        compute_us=round((perf_counter() - t0) * 1e6, 3),
        **({} if span is None else {"lineage": span}),
    )
    return value
