"""Fleet observability plane: cross-pod telemetry federation.

PR 18 federated *state* — the metric values themselves fold across pods. This
module federates the *evidence*: every pod already exports counters
(``engine/stats.py``), latency distributions (``diag/hist.py``), sentinel
health bitmasks, and the cost-ledger rollup on its own ``/metrics``; nobody
could answer "what is the FLEET-wide p99 sync latency" or "which pod is
breaching" without hand-joining N scrapes. Now the fleet tier answers
directly:

- **Telemetry envelope** (:func:`pack_telemetry` / :func:`parse_telemetry`):
  one pod's observability surface as a self-verifying ``.npz`` payload —
  layout-version stamp, order-independent payload CRC (the federation
  :func:`~torchmetrics_tpu.serve.federation._payload_crc`, reused verbatim),
  and a monotonic sequence watermark — served by the sidecar as
  ``GET /telemetry.bin`` with the same version/CRC/seq headers ``/state``
  stamps. Histograms travel as raw bucket-count vectors over the shared
  geometric :data:`~torchmetrics_tpu.diag.hist.BOUNDS`
  (:func:`~torchmetrics_tpu.diag.hist.hist_to_arrays`), so no boundary data
  moves and the merge is exact bucket addition.
- **Aggregator** (:class:`FleetTelemetry`): rides the federation membership
  idioms — pods are URLs or callables, every fetch runs through
  :func:`~torchmetrics_tpu.parallel.resilience.bounded_pull` on a
  ``fleet-pull:<pod>`` label (deadline, retries, typed fault classification,
  chaos-injection hook), a lost pod is a counted ``fleet.degraded`` event and
  an exclusion — never a hang, never an exception out of the round — and a
  stale sequence number is rejected at the watermark (``fleet.stale``).
- **Merge semantics** (:meth:`FleetTelemetry.merge`): counters SUM; histograms
  merge bucket-wise via :func:`~torchmetrics_tpu.diag.hist.merge_hists` —
  exactly the union-stream histogram, so the ≤ 18.92 % one-sided quantile
  error bound (``GROWTH = 2**0.25``) is *preserved* by federation, asserted in
  ``tests/test_fleet.py`` and the ``fleet`` bench scenario; sentinel bitmasks
  OR per owner; fallback/retrace/flush reason maps merge key-wise by sum;
  ledger totals sum (``peak_bytes_max`` folds by max). Per-pod
  liveness/seq-lag/staleness/uptime gauges ride alongside the merged view.
- **Fleet exposition** (:meth:`FleetTelemetry.export_prometheus`): pod-labeled
  per-pod series for the curated hot-path counters plus aggregated
  ``tm_tpu_fleet_*`` families (gauges, counters, and PROPER histogram
  exposition for the merged distributions), byte-stable under pod ingest
  order — merging is commutative and pods render in canonical id order.
- **Fleet SLOs**: the aggregator owns its own
  :class:`~torchmetrics_tpu.diag.slo.SLOEngine` instance and evaluates the
  SAME :data:`~torchmetrics_tpu.diag.slo.SLO_REGISTRY` specs over the merged
  inputs (:meth:`FleetTelemetry.evaluate_slos`) — one objective language for
  one pod or forty. ``serve/sidecar.py`` exposes the result as
  ``/fleet/metrics`` and ``/fleet/slo``.

Env knob (fail-loud): ``TORCHMETRICS_TPU_FLEET_PULL_MS`` — per-pull deadline
in milliseconds for :meth:`FleetTelemetry.pull_round` (unset/0 = no
deadline), parsed by :func:`torchmetrics_tpu.serve.stats.fleet_pull_ms`.
"""

from __future__ import annotations

import io
import json
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

from torchmetrics_tpu.diag import lineage as _lineage
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.diag.hist import (
    Histogram,
    hist_from_arrays,
    hist_to_arrays,
    merge_hists,
)
from torchmetrics_tpu.diag.slo import SLOEngine
from torchmetrics_tpu.engine.stats import _COUNTER_FIELDS, EngineStats
from torchmetrics_tpu.parallel.elastic import SnapshotIntegrityError, SnapshotVersionError
from torchmetrics_tpu.parallel.resilience import (
    SyncFaultError,
    bounded_pull,
    resilience_context,
)
from torchmetrics_tpu.serve import stats as _serve_stats
from torchmetrics_tpu.serve.federation import (
    CRC_HEADER,
    SEQ_HEADER,
    VERSION_HEADER,
    _http_fetcher,
    _payload_crc,
)
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "FLEET_LAYOUT_VERSION",
    "FleetTelemetry",
    "PodTelemetry",
    "local_telemetry",
    "pack_telemetry",
    "parse_telemetry",
]

#: telemetry-envelope layout version — bumped on any change to the key scheme,
#: the JSON blob layout, or the CRC coverage; a mismatch is a typed refusal
FLEET_LAYOUT_VERSION = 1

_HIST_KEY = "hist"  # npz key prefix: hist::{owner}::{kind}::{series}
_META_KEY = "histmeta"  # float64 [total, sum, min, max] sibling of each hist

#: reason-map names merged key-wise across pods (EngineStats Counter attrs)
_REASON_MAPS = ("fallback_reasons", "retrace_causes", "scan_flush_reasons")

#: ledger-totals field folded by MAX instead of sum (a peak is not additive)
_LEDGER_MAX_FIELDS = ("peak_bytes_max",)

# process start reference for the uptime stamp
_T0 = time.monotonic()


@dataclass
class PodTelemetry:
    """One pod's verified telemetry envelope, parsed back into merge-ready form."""

    counters: Dict[str, int]
    reasons: Dict[str, Dict[str, int]]  # map name -> {reason: count}
    sentinels: List[Dict[str, Any]]  # [{"owner": ..., "flags": bitmask}, ...]
    ledger_totals: Dict[str, float]
    hists: Dict[Tuple[str, str, str], Histogram]  # (owner, kind, series)
    seq: int
    uptime_s: float


def local_telemetry(seq: Optional[int] = None) -> Dict[str, Any]:
    """This process's telemetry surface as one pack-ready dict.

    ``seq`` defaults to the summed engine counters — monotonic between resets,
    which is all the aggregator's watermark dedupe needs. Emulated pods (bench,
    tests) build synthetic dicts of the same shape instead.
    """
    from torchmetrics_tpu.diag.costs import ledger_snapshot
    from torchmetrics_tpu.diag.hist import histogram_items
    from torchmetrics_tpu.diag.sentinel import sentinel_report
    from torchmetrics_tpu.engine.stats import engine_report

    report = engine_report()
    counters = {f: int(report.get(f, 0)) for f in _COUNTER_FIELDS}
    if seq is None:
        seq = sum(counters.values())
    return {
        "counters": counters,
        "reasons": {name: dict(report.get(name, {})) for name in _REASON_MAPS},
        "sentinels": [
            {"owner": s["owner"], "flags": int(s["flags"])} for s in sentinel_report()
        ],
        "ledger_totals": {k: float(v) for k, v in ledger_snapshot()["totals"].items()},
        "hists": {key: hist for key, hist in histogram_items()},
        "seq": int(seq),
        "uptime_s": time.monotonic() - _T0,
    }


# tmlint: host-only — histogram counts are python lists; nothing device-backed
def pack_telemetry(
    snapshot: Optional[Dict[str, Any]] = None, seq: Optional[int] = None
) -> Tuple[bytes, Dict[str, str]]:
    """Serialize one pod's telemetry into a self-verifying envelope.

    Returns ``(payload_bytes, headers)`` with the same version/CRC/seq header
    contract the ``/state`` federation envelope carries — the sidecar serves
    the bytes as ``GET /telemetry.bin`` and stamps the headers verbatim.
    """
    snap = snapshot if snapshot is not None else local_telemetry(seq=seq)
    flat: Dict[str, np.ndarray] = {}
    hist_keys: List[List[str]] = []
    for (owner, kind, series), hist in sorted(snap.get("hists", {}).items()):
        counts, meta = hist_to_arrays(hist)
        flat[f"{_HIST_KEY}::{owner}::{kind}::{series}"] = np.asarray(counts, dtype=np.int64)
        flat[f"{_META_KEY}::{owner}::{kind}::{series}"] = np.asarray(meta, dtype=np.float64)
        hist_keys.append([owner, kind, series])
    blob = {
        "counters": snap.get("counters", {}),
        "reasons": snap.get("reasons", {}),
        "sentinels": snap.get("sentinels", []),
        "ledger_totals": snap.get("ledger_totals", {}),
        "uptime_s": float(snap.get("uptime_s", 0.0)),
        "hist_keys": hist_keys,
    }
    flat["__json__"] = np.frombuffer(
        json.dumps(blob, sort_keys=True).encode(), dtype=np.uint8
    ).copy()
    env_seq = int(snap.get("seq", 0)) if seq is None else int(seq)
    flat["__fleet_version__"] = np.int64(FLEET_LAYOUT_VERSION)
    flat["__seq__"] = np.int64(env_seq)
    crc = _payload_crc(flat)
    flat["__crc__"] = np.uint32(crc)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    headers = {
        VERSION_HEADER: str(FLEET_LAYOUT_VERSION),
        CRC_HEADER: f"{crc:#010x}",
        SEQ_HEADER: str(env_seq),
    }
    rows = [r for r in _lineage.lineage_snapshot()["owners"].values()]
    if rows:
        # the telemetry envelope carries this pod's provenance ledger as a
        # header stamp — the fleet aggregator (or curl -I) audits per-owner
        # freshness without unpacking the npz
        headers[_lineage.LINEAGE_HEADER] = _lineage.encode_lineage_header(rows)
    return buf.getvalue(), headers


# tmlint: host-only — the payload is wire bytes; no device buffer reaches this
def parse_telemetry(data: bytes, headers: Optional[Mapping[str, str]] = None) -> PodTelemetry:
    """Verify a telemetry envelope (version, CRC, header cross-check), parse it.

    The same typed refusal contract as the state envelope: unreadable payloads
    and CRC mismatches raise :class:`~torchmetrics_tpu.parallel.elastic.
    SnapshotIntegrityError`, a layout-version mismatch raises
    :class:`~torchmetrics_tpu.parallel.elastic.SnapshotVersionError`.
    """
    if headers:
        raw_version = headers.get(VERSION_HEADER)
        if raw_version is not None and int(raw_version) != FLEET_LAYOUT_VERSION:
            raise SnapshotVersionError(
                f"pod telemetry advertises layout version {raw_version}, this build"
                f" reads {FLEET_LAYOUT_VERSION} — refusing to guess at the layout"
            )
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as npz:
            flat = {k: np.asarray(npz[k]) for k in npz.files}
    except Exception as err:  # noqa: BLE001 — unreadable IS the corruption signal
        raise SnapshotIntegrityError(f"pod telemetry payload is unreadable: {err}") from err
    for key in ("__fleet_version__", "__seq__", "__crc__", "__json__"):
        if key not in flat:
            raise SnapshotIntegrityError(
                f"pod telemetry payload lacks the {key} stamp — not a fleet envelope"
            )
    version = int(flat["__fleet_version__"])
    if version != FLEET_LAYOUT_VERSION:
        raise SnapshotVersionError(
            f"pod telemetry has layout version {version}, this build reads"
            f" {FLEET_LAYOUT_VERSION} — refusing to guess at the layout"
        )
    expected = int(flat["__crc__"])
    actual = _payload_crc(flat)
    if actual != expected:
        raise SnapshotIntegrityError(
            f"pod telemetry failed its integrity check (crc {actual:#010x} !="
            f" stamped {expected:#010x}) — the payload is corrupt"
        )
    if headers:
        raw_crc = headers.get(CRC_HEADER)
        if raw_crc is not None and int(raw_crc, 0) != expected:
            raise SnapshotIntegrityError(
                f"pod telemetry header CRC {raw_crc} disagrees with the payload stamp"
                f" {expected:#010x} — the transport delivered a different payload"
            )
    blob = json.loads(bytes(flat["__json__"]).decode())
    hists: Dict[Tuple[str, str, str], Histogram] = {}
    for owner, kind, series in blob.get("hist_keys", []):
        counts = flat[f"{_HIST_KEY}::{owner}::{kind}::{series}"]
        meta = flat[f"{_META_KEY}::{owner}::{kind}::{series}"]
        hists[(owner, kind, series)] = hist_from_arrays(counts.tolist(), meta.tolist())
    return PodTelemetry(
        counters={k: int(v) for k, v in blob.get("counters", {}).items()},
        reasons={
            name: {k: int(v) for k, v in rows.items()}
            for name, rows in blob.get("reasons", {}).items()
        },
        sentinels=list(blob.get("sentinels", [])),
        ledger_totals={k: float(v) for k, v in blob.get("ledger_totals", {}).items()},
        hists=hists,
        seq=int(flat["__seq__"]),
        uptime_s=float(blob.get("uptime_s", 0.0)),
    )


@dataclass
class _FleetSlot:
    """The latest verified telemetry held for one pod."""

    telemetry: PodTelemetry
    ts: float  # time.monotonic() at ingest — drives the staleness watermark


class FleetTelemetry:
    """Pull, verify, and merge N pods' telemetry envelopes into one plane.

    Args:
        pods: ``{pod_id: source}`` where source is a ``/telemetry.bin`` URL
            (string) or a zero-arg callable returning ``bytes`` or
            ``(bytes, headers)`` — callables let tests and benches emulate
            pods without sockets. A :class:`~torchmetrics_tpu.serve.
            federation.FederationAggregator` may be passed as ``aggregator``
            to reuse its membership (pod ids + ``/state`` URLs rewritten to
            ``/telemetry.bin``).
        staleness_s: telemetry older than this (since ingest) is excluded
            from merges as degraded. Default:
            ``TORCHMETRICS_TPU_FEDERATION_STALENESS_S`` (unset = no bound).
        pull_ms: per-pull deadline for :meth:`pull_round`. Default:
            ``TORCHMETRICS_TPU_FLEET_PULL_MS`` (unset/0 = no deadline).
        retries: bounded-pull retry budget. Default:
            ``TORCHMETRICS_TPU_FEDERATION_RETRIES`` (2).
    """

    def __init__(
        self,
        pods: Optional[Mapping[str, Any]] = None,
        aggregator: Optional[Any] = None,
        staleness_s: Optional[float] = None,
        pull_ms: Optional[float] = None,
        retries: Optional[int] = None,
    ) -> None:
        from torchmetrics_tpu.parallel.resilience import _env_float

        self.pods: Dict[str, Any] = dict(pods or {})
        if aggregator is not None:
            for pid, source in aggregator.pods.items():
                self.pods.setdefault(
                    pid,
                    source.replace("/state", "/telemetry.bin")
                    if isinstance(source, str)
                    else source,
                )
        if not self.pods:
            raise TorchMetricsUserError(
                "FleetTelemetry needs at least one pod source (a /telemetry.bin"
                " URL or a callable) — an empty membership has nothing to merge."
            )
        self.staleness_s = (
            _env_float("TORCHMETRICS_TPU_FEDERATION_STALENESS_S")
            if staleness_s is None
            else float(staleness_s)
        )
        self.pull_ms = _serve_stats.fleet_pull_ms() if pull_ms is None else float(pull_ms)
        self.retries = _serve_stats.federation_retries() if retries is None else int(retries)
        self.stats = EngineStats("fleet")
        self.slo = SLOEngine("fleet-slo")
        self._lock = threading.Lock()
        self._slots: Dict[str, _FleetSlot] = {}  # guarded-by: _lock
        self._watermarks: Dict[str, int] = {}  # guarded-by: _lock
        self._excluded: set = set()  # guarded-by: _lock — pods out of the last round
        self._last_pods = 0  # guarded-by: _lock
        self._last_degraded = 0  # guarded-by: _lock
        _serve_stats.register_fleet(self)

    # ------------------------------------------------------------------ ingest

    def ingest(self, pod_id: str, data: bytes, headers: Optional[Mapping[str, str]] = None) -> bool:
        """Verify and accept one pod telemetry envelope (push path).

        Returns True when the envelope advanced the pod's watermark; False
        when the watermark dedupe rejected it as stale (counted, evented,
        never merged twice).
        """
        telemetry = parse_telemetry(data, headers)
        with self._lock:
            prev = self._watermarks.get(pod_id)
            if prev is not None and telemetry.seq <= prev:
                _diag.record(
                    "fleet.stale", "fleet",
                    pod=pod_id, seq=telemetry.seq, watermark=prev,
                )
                return False
            self._excluded.discard(pod_id)
            self._slots[pod_id] = _FleetSlot(telemetry=telemetry, ts=time.monotonic())
            self._watermarks[pod_id] = telemetry.seq
            self.stats.fleet_pulls += 1
        _diag.record(
            "fleet.pull", "fleet", pod=pod_id, seq=telemetry.seq, bytes=len(data),
        )
        return True

    def pull_round(self) -> Dict[str, bool]:
        """Pull every pod's ``/telemetry.bin`` once (bounded, classified).

        Same contract as the federation round: each fetch rides
        :func:`~torchmetrics_tpu.parallel.resilience.bounded_pull` under a
        ``fleet-pull:<pod>`` label — deadline watchdog, retry/backoff, typed
        fault classification, and the chaos-injection hook. A terminally
        failed pod is excluded (``fleet.degraded``, counted) until it is
        ingested again; the round never raises for one lost pod.
        """
        pod_ids = sorted(self.pods)
        member_idx = {pid: i for i, pid in enumerate(pod_ids)}
        results: Dict[str, bool] = {}
        timeout_s = self.pull_ms / 1e3 if self.pull_ms else None
        with resilience_context(deadline_ms=self.pull_ms, retries=self.retries):
            for pid in pod_ids:
                source = self.pods[pid]
                fetch = source if callable(source) else _http_fetcher(source, timeout_s)
                try:
                    out = bounded_pull(
                        fetch,
                        label=f"fleet-pull:{pid}",
                        rank=member_idx[pid],
                        members=[member_idx[pid]],
                    )
                except SyncFaultError as exc:
                    with self._lock:
                        self._excluded.add(pid)
                        self.stats.fleet_degraded_pulls += 1
                    _diag.record(
                        "fleet.degraded", "fleet",
                        pod=pid, reason=type(exc).__name__, attempts=exc.attempts,
                    )
                    results[pid] = False
                    continue
                data, headers = out if isinstance(out, tuple) else (out, None)
                results[pid] = self.ingest(pid, data, headers)
        return results

    # ------------------------------------------------------------------ merge

    def _fresh_membership(self) -> Tuple[Dict[str, _FleetSlot], List[str], List[Tuple[str, str]]]:
        now = time.monotonic()
        with self._lock:
            slots = dict(self._slots)
            known = sorted(set(self.pods) | set(slots))
        fresh: Dict[str, _FleetSlot] = {}
        for pid in sorted(slots):
            slot = slots[pid]
            if self.staleness_s is not None and now - slot.ts > self.staleness_s:
                continue
            fresh[pid] = slot
        members = sorted(fresh)
        excluded = [
            (pid, "stale" if pid in slots else "missing") for pid in known if pid not in fresh
        ]
        return fresh, members, excluded

    def merge(self) -> Dict[str, Any]:
        """One fleet-wide telemetry merge over the fresh membership.

        Counters sum; histograms merge bucket-wise per series (the exact
        union-stream histogram — the GROWTH quantile bound is preserved);
        sentinel bitmasks OR per owner; reason maps merge key-wise by sum;
        ledger totals sum with ``peak_bytes_max`` folded by max. Excluded
        pods (stale, unreachable, never pulled) are counted and evented —
        degraded, never wrong, never hung. Raises
        :class:`~torchmetrics_tpu.utilities.exceptions.TorchMetricsUserError`
        when no pod has ever been verified (nothing to answer with).
        """
        fresh, members, excluded = self._fresh_membership()
        if not members:
            raise TorchMetricsUserError(
                "Fleet merge has no verified pod telemetry to merge — ingest or"
                " pull at least one pod before asking for a fleet view."
            )
        counters: Dict[str, int] = {f: 0 for f in _COUNTER_FIELDS}
        reasons: Dict[str, Dict[str, int]] = {name: {} for name in _REASON_MAPS}
        sentinels: Dict[str, int] = {}
        ledger: Dict[str, float] = {}
        series_hists: Dict[str, Histogram] = {}
        pods_view: Dict[str, Dict[str, Any]] = {}
        now = time.monotonic()
        max_seq = max(fresh[pid].telemetry.seq for pid in members)
        for pid in members:
            slot = fresh[pid]
            tel = slot.telemetry
            for f in _COUNTER_FIELDS:
                counters[f] += tel.counters.get(f, 0)
            for name in _REASON_MAPS:
                merged = reasons[name]
                for reason, n in tel.reasons.get(name, {}).items():
                    merged[reason] = merged.get(reason, 0) + int(n)
            for row in tel.sentinels:
                owner = str(row.get("owner", ""))
                sentinels[owner] = sentinels.get(owner, 0) | int(row.get("flags", 0))
            for key, value in tel.ledger_totals.items():
                if key in _LEDGER_MAX_FIELDS:
                    ledger[key] = max(ledger.get(key, 0.0), value)
                else:
                    ledger[key] = ledger.get(key, 0.0) + value
            for (_owner, _kind, series), hist in tel.hists.items():
                prev = series_hists.get(series)
                series_hists[series] = hist if prev is None else merge_hists(prev, hist)
            pods_view[pid] = {
                "up": 1,
                "seq": tel.seq,
                "seq_lag": max_seq - tel.seq,
                "staleness_s": now - slot.ts,
                "uptime_s": tel.uptime_s,
            }
        for pid, reason in excluded:
            pods_view[pid] = {"up": 0, "reason": reason}
        with self._lock:
            self._excluded.update(pid for pid, _ in excluded)
            self._last_pods = len(members)
            self._last_degraded = len(excluded)
            self.stats.fleet_merges += 1
            self.stats.fleet_degraded_pulls += sum(
                1 for _pid, reason in excluded if reason == "stale"
            )
        for pid, reason in excluded:
            _diag.record("fleet.degraded", "fleet", pod=pid, reason=reason)
        # coverage attestation: the merged view carries its own membership
        # stamp (pods + telemetry seqs in, exclusions + reasons out) — a
        # 3/4-pod fleet number is visibly a 3/4-pod number
        coverage = _lineage.note_coverage(
            "fleet",
            members,
            seqs={pid: fresh[pid].telemetry.seq for pid in members},
            excluded=excluded,
        )
        _diag.record(
            "fleet.merge", "fleet",
            pods=len(members), degraded=len(excluded), members=",".join(members),
        )
        return {
            "pods": pods_view,
            "members": members,
            "degraded": [pid for pid, _ in excluded],
            "counters": counters,
            "reasons": {name: dict(sorted(rows.items())) for name, rows in reasons.items()},
            "sentinels": dict(sorted(sentinels.items())),
            "ledger_totals": dict(sorted(ledger.items())),
            "histograms": series_hists,
            "coverage": coverage or {},
        }

    # ------------------------------------------------------------------ SLOs

    def evaluate_slos(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Evaluate the shared SLO registry over the MERGED fleet inputs.

        The same specs the per-pod singleton evaluates, fed with the summed
        counters (aggregator-side fleet counters overlaid — a pod cannot see
        its own exclusion) and the merged per-series histograms.
        """
        merged = self.merge()
        counters = dict(merged["counters"])
        for f in ("fleet_pulls", "fleet_merges", "fleet_degraded_pulls"):
            counters[f] = counters.get(f, 0) + getattr(self.stats, f)
        hists = merged["histograms"]

        def series_fn(name: str) -> Histogram:
            return hists.get(name) or Histogram()

        return self.slo.evaluate(
            inputs={"counters": counters, "series": series_fn}, now=now
        )

    # ------------------------------------------------------------------ views

    def fleet_state(self) -> Dict[str, int]:
        """The telemetry gauge row (``serve/stats.py`` registry contract)."""
        with self._lock:
            if self._last_pods:
                return {"pods": self._last_pods, "degraded_pods": self._last_degraded}
            return {"pods": len(self._slots), "degraded_pods": len(self._excluded)}

    #: curated per-pod counter families for the fleet exposition: the hot-path
    #: health surface, not all ~70 fields — the full set rides each pod's own
    #: /metrics; the fleet view answers "which pod is sick"
    _POD_COUNTERS = (
        "dispatches", "eager_fallbacks", "sync_degraded_folds", "quarantined_batches",
    )

    def export_prometheus(self, path: Optional[str] = None) -> str:
        """Render the fleet view as Prometheus text exposition format.

        Byte-stable under pod ingest order: merges are commutative and every
        sample set renders in canonical (pod id, label) order. Pod ids are
        caller-supplied strings — every label value goes through the
        exposition escaping (backslash, double-quote, newline).
        """
        from torchmetrics_tpu.diag.telemetry import _HIST_SERIES, _PREFIX, _sample

        merged = self.merge()
        slo_rows = self.slo.state()
        lines: List[str] = []

        def emit(name: str, mtype: str, help_text: str, samples) -> None:
            if not samples:
                return
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                lines.append(_sample(name, labels, value))

        pods_view = merged["pods"]
        emit(f"{_PREFIX}_fleet_pods", "gauge",
             "pods with fresh verified telemetry in the fleet membership",
             [({}, len(merged["members"]))])
        emit(f"{_PREFIX}_fleet_degraded_pods", "gauge",
             "pods excluded from the last fleet merge (stale/unreachable)",
             [({}, len(merged["degraded"]))])
        emit(f"{_PREFIX}_fleet_pod_up", "gauge",
             "1 when the pod's telemetry is in the fresh membership",
             [({"pod": pid}, row["up"]) for pid, row in sorted(pods_view.items())])
        fresh_rows = [(pid, row) for pid, row in sorted(pods_view.items()) if row["up"]]
        emit(f"{_PREFIX}_fleet_pod_seq", "gauge",
             "the pod's last verified telemetry sequence watermark",
             [({"pod": pid}, row["seq"]) for pid, row in fresh_rows])
        emit(f"{_PREFIX}_fleet_pod_seq_lag", "gauge",
             "sequence distance behind the most-advanced fleet member",
             [({"pod": pid}, row["seq_lag"]) for pid, row in fresh_rows])
        emit(f"{_PREFIX}_fleet_pod_staleness_seconds", "gauge",
             "age of the pod's last verified telemetry at merge time",
             [({"pod": pid}, row["staleness_s"]) for pid, row in fresh_rows])
        emit(f"{_PREFIX}_fleet_pod_uptime_seconds", "gauge",
             "the pod's self-reported process uptime",
             [({"pod": pid}, row["uptime_s"]) for pid, row in fresh_rows])

        # per-pod curated counters (pod-labeled) + the fleet-wide sums
        fresh, members, _ = self._fresh_membership()
        for field in self._POD_COUNTERS:
            emit(f"{_PREFIX}_{field}_total", "counter",
                 f"per-pod {field.replace('_', ' ')} (fleet view)",
                 [({"pod": pid}, fresh[pid].telemetry.counters.get(field, 0))
                  for pid in members])
            emit(f"{_PREFIX}_fleet_{field}_total", "counter",
                 f"fleet-wide {field.replace('_', ' ')} (summed over fresh pods)",
                 [({}, merged["counters"].get(field, 0))])

        emit(f"{_PREFIX}_sentinel_flags", "gauge",
             "fleet-ORed health-sentinel bitmask per metric (0 = healthy)",
             [({"owner": owner}, flags)
              for owner, flags in sorted(merged["sentinels"].items())])

        # merged distributions as PROPER histogram exposition under
        # tm_tpu_fleet_* names (the unit suffix stays terminal)
        for series, (name, scale, help_text) in sorted(
            _HIST_SERIES.items(), key=lambda kv: kv[1][0]
        ):
            hist = merged["histograms"].get(series)
            if hist is None or not hist.total:
                continue
            family = f"{_PREFIX}_fleet_{name}"
            lines.append(f"# HELP {family} fleet-merged {help_text}")
            lines.append(f"# TYPE {family} histogram")
            for bound, cum in hist.nonempty_buckets():
                le = "+Inf" if bound is None else repr(bound * scale)
                lines.append(_sample(f"{family}_bucket", {"le": le}, cum))
            lines.append(_sample(f"{family}_sum", {}, hist.sum * scale))
            lines.append(_sample(f"{family}_count", {}, hist.total))

        emit(f"{_PREFIX}_slo_compliance", "gauge",
             "1 when the fleet-evaluated SLO is compliant, 0 in breach",
             [({"slo": row["id"]}, 0 if row["breaching"] else 1) for row in slo_rows])
        emit(f"{_PREFIX}_slo_breaching", "gauge",
             "1 when the fleet-evaluated SLO is in breach (blocking SLOs gate /healthz)",
             [({"slo": row["id"]}, 1 if row["breaching"] else 0) for row in slo_rows])

        text = "\n".join(lines) + "\n" if lines else ""
        if path is not None:
            with open(path, "w") as fh:
                fh.write(text)
        return text
