"""Threaded scrape endpoint: the PR-4 Prometheus exporter as a real sidecar.

``diag/telemetry.py`` renders exposition text; this module serves it. A
:class:`MetricsSidecar` binds a ``ThreadingHTTPServer`` on a daemon thread —
stdlib only, no new dependencies — and answers:

- ``GET /metrics``   → ``export_prometheus()`` text,
  ``Content-Type: text/plain; version=0.0.4`` (the exposition-format
  version a Prometheus scraper negotiates);
- ``GET /telemetry`` → one ``telemetry_snapshot()`` as a JSON line
  (``application/json``), the JSONL tail-dashboard feed;
- ``GET /healthz``   → READINESS, not unconditional liveness: ``200 ok`` only
  when the warm-start handoff (if any) fully replayed AND no *blocking* SLO
  (``diag/slo.py``) is in breach — otherwise ``503`` with a JSON body naming
  the reason and the breaching SLO, so an orchestrator's readiness probe
  drains traffic from a pod that is up but failing its objectives;
- ``GET /slo``       → one SLO evaluation pass + the per-spec compliance rows
  (``application/json``);
- ``GET /state``     → the versioned federation envelope for the sidecar's
  ``state_target`` metrics (``serve/federation.py``): packed snapshot bytes
  with layout-version, payload-CRC, and snapshot-sequence headers, built on
  the pause-free :func:`~torchmetrics_tpu.serve.snapshot.take_snapshot` —
  answering never stalls the training thread. Until a consistent snapshot
  exists the endpoint answers **503 with a typed JSON reason**, never an
  empty 200 an aggregator would mistake for a zero-valued pod;
- ``GET /telemetry.bin`` → the versioned fleet TELEMETRY envelope
  (``serve/fleet.py``): this pod's counters + histogram registries +
  sentinel bits + ledger rollup, CRC/version/seq stamped exactly like
  ``/state`` — what a :class:`~torchmetrics_tpu.serve.fleet.FleetTelemetry`
  aggregator pulls;
- ``GET /fleet/metrics`` / ``GET /fleet/slo`` → the FLEET-side surfaces when
  a fleet aggregator is attached (``fleet_target``): the merged pod-labeled
  exposition, and an SLO evaluation over the merged fleet inputs. Without an
  attached aggregator both answer ``503 {"reason": "no-fleet-target"}``.

Every scrape is timed into the ``serve_scrape_latency_seconds`` histogram
family (``diag/hist.py``) and the ``tm_tpu_serve_scrapes_total`` counters;
scrape handlers run on server threads, so the hot update loop never blocks
on a scraper — pair with :func:`~torchmetrics_tpu.serve.snapshot.
snapshot_compute` for value reads.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from time import perf_counter
from typing import Any, Optional

from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import lineage as _lineage
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.serve import stats as _serve_stats

__all__ = ["MetricsSidecar", "PROMETHEUS_CONTENT_TYPE"]

#: text exposition format 0.0.4 — what a Prometheus server's Accept header
#: negotiates for the classic text format
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4"


def _scrape_flush() -> None:
    """Drain every scan queue before the scrape reads counters/gauges.

    The pause-free contract (the snapshot-compute discipline applied to
    scrapes): with async dispatch on, ``drain()`` routes the buffers through
    the BACKGROUND worker and only this scrape thread waits on the join — the
    training thread contends solely on the brief buffer swap, so a Prometheus
    scrape can never stall a training step. The scrape still observes the
    flush-on-observation watermark: every step enqueued before the scrape is
    folded into what it exports. Synchronous mode keeps the pre-async
    behavior (the drain runs here, on the scrape thread — not the hot loop's).
    """
    from torchmetrics_tpu.engine.async_dispatch import _engaged
    from torchmetrics_tpu.engine.scan import flush_all

    drained = flush_all("observation:scrape")
    if _engaged:
        # narrate the pause-free route: the steps this scrape waited out rode
        # the background worker, not this thread's dispatch
        _diag.record("serve.scrape.async", "sidecar", drained=drained)
    # the scrape observes every owner: after the drain+join above, each
    # watermark's folded count is exactly the steps this export reflects
    _lineage.observe_all("scrape")


class _ScrapeHandler(BaseHTTPRequestHandler):
    server_version = "tm-tpu-sidecar/1.0"

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
        t0 = perf_counter()
        path = self.path.split("?", 1)[0]
        status = 200
        extra_headers: dict = {}
        try:
            if path == "/state":
                status, extra_headers, body, ctype = self._state_response()
            elif path in ("/metrics", "/"):
                from torchmetrics_tpu.diag.telemetry import export_prometheus

                # drain-before-scrape (engine/scan.py): counters and gauges a
                # scraper sees must reflect every enqueued step — the flush is
                # recorded (scan.flush, reason=observation:scrape) so diag can
                # prove no stale-read path exists
                _scrape_flush()
                body = export_prometheus().encode()
                ctype = PROMETHEUS_CONTENT_TYPE
            elif path == "/telemetry":
                from torchmetrics_tpu.diag.telemetry import telemetry_snapshot

                _scrape_flush()
                body = (json.dumps(telemetry_snapshot(), sort_keys=True, default=str) + "\n").encode()
                ctype = "application/json"
            elif path == "/healthz":
                status, body, ctype = self._healthz_response()
            elif path == "/slo":
                from torchmetrics_tpu.diag.slo import evaluate_slos

                body = (json.dumps(evaluate_slos(), sort_keys=True) + "\n").encode()
                ctype = "application/json"
            elif path == "/telemetry.bin":
                from torchmetrics_tpu.serve.fleet import pack_telemetry

                body, extra_headers = pack_telemetry()
                ctype = "application/octet-stream"
            elif path == "/fleet/metrics":
                status, body, ctype = self._fleet_response("metrics")
            elif path == "/fleet/slo":
                status, body, ctype = self._fleet_response("slo")
            else:
                self.send_error(404, "unknown scrape path")
                return
        except Exception as exc:  # noqa: BLE001 — a scrape failure must answer, not hang
            self.send_error(500, f"{type(exc).__name__}: {exc}")
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for name, value in extra_headers.items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)
        elapsed = perf_counter() - t0
        _serve_stats.note_scrape(elapsed)
        _hist.observe("sidecar", "serve", "scrape_us", round(elapsed * 1e6, 3))
        _diag.record("serve.scrape", "sidecar", path=path, status=status, bytes=len(body))

    def _state_response(self) -> tuple:
        """The versioned ``/state`` endpoint: one federation envelope.

        A pod that cannot yet answer CONSISTENTLY says so — ``503`` with a
        typed JSON reason (``no-state-target`` when the sidecar serves no
        metrics, ``snapshot-inconsistent`` when the update loop never
        quiesced within the retry budget) — never an empty ``200`` a naive
        aggregator would fold as a zero-valued pod.
        """
        from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

        target = getattr(self.server, "tm_state_target", None)
        if target is None:
            reason = json.dumps({"reason": "no-state-target"}) + "\n"
            return 503, {}, reason.encode(), "application/json"
        from torchmetrics_tpu.serve.federation import pack_envelope

        try:
            body, headers = pack_envelope(target)
        except TorchMetricsUserError as exc:
            reason = json.dumps({"reason": "snapshot-inconsistent", "detail": str(exc)}) + "\n"
            return 503, {}, reason.encode(), "application/json"
        return 200, headers, body, "application/octet-stream"

    def _healthz_response(self) -> tuple:
        """Readiness over warm-start status + blocking SLOs.

        Failure modes answer ``503`` with a JSON body NAMING the cause — a
        warm handoff that failed to replay (``warm-start-failed``, the pod is
        up but cold and possibly state-less) or a blocking SLO in breach
        (``slo-breach`` with the breaching ids) — so an orchestrator can
        drain traffic for the right reason. Liveness is the socket answering
        at all; readiness is this body.
        """
        warm = getattr(self.server, "tm_warm_report", None)
        if warm and int(warm.get("failed", 0)) > 0:
            body = json.dumps({
                "status": "unready",
                "reason": "warm-start-failed",
                "failed": int(warm.get("failed", 0)),
                "replayed": int(warm.get("replayed", 0)),
            }, sort_keys=True) + "\n"
            return 503, body.encode(), "application/json"
        from torchmetrics_tpu.diag.slo import blocking_breaches, evaluate_slos, slo_enabled

        if slo_enabled():
            evaluate_slos()
            breaching = blocking_breaches()
            if breaching:
                payload = {
                    "status": "unready",
                    "reason": "slo-breach",
                    "slo": breaching,
                }
                if "value-freshness" in breaching:
                    # name the owner serving stale values, not just the SLO id:
                    # an operator draining this pod needs to know WHICH metric's
                    # fold watermark fell behind and by how much
                    stale = _lineage.stalest_owner()
                    if stale is not None:
                        owner, behind, wall_us = stale
                        payload["stale_owner"] = owner
                        payload["staleness_steps"] = int(behind)
                        payload["staleness_seconds"] = round(wall_us * 1e-6, 6)
                body = json.dumps(payload, sort_keys=True) + "\n"
                return 503, body.encode(), "application/json"
        return 200, b"ok\n", "text/plain"

    def _fleet_response(self, view: str) -> tuple:
        """The fleet-side surfaces: merged exposition or fleet SLO rows.

        Mirrors the ``/state`` contract — no attached aggregator is a typed
        ``503 no-fleet-target`` refusal, never an empty fleet pretending to
        be a healthy one.
        """
        fleet = getattr(self.server, "tm_fleet_target", None)
        if fleet is None:
            reason = json.dumps({"reason": "no-fleet-target"}) + "\n"
            return 503, reason.encode(), "application/json"
        if view == "metrics":
            return 200, fleet.export_prometheus().encode(), PROMETHEUS_CONTENT_TYPE
        rows = fleet.evaluate_slos()
        return 200, (json.dumps(rows, sort_keys=True) + "\n").encode(), "application/json"

    def log_message(self, *_: Any) -> None:
        """Silence the default stderr access log (scrapes are periodic)."""


class MetricsSidecar:
    """Daemon-thread HTTP scrape endpoint over the telemetry exporters.

    Usage::

        with MetricsSidecar() as sidecar:      # port 0 = ephemeral
            print(sidecar.url)                 # http://127.0.0.1:PORT/metrics
            ... hot loop keeps updating ...

    ``port`` defaults to ``TORCHMETRICS_TPU_SERVE_PORT`` (0 → OS-assigned,
    read back from :attr:`port` after :meth:`start`).

    Warm-replica handoff: pass ``warm_target`` (a Metric or MetricCollection)
    to run :func:`~torchmetrics_tpu.engine.persist.warm_start` during
    :meth:`start`, BEFORE the endpoint answers its first scrape — the prewarm
    manifest replays every recorded executable signature out of the
    persistent cache (``persist_dir`` overrides ``TORCHMETRICS_TPU_PERSIST``)
    and ``snapshot_dir`` additionally restores the newest elastic snapshot,
    so a replacement pod comes up serving-identical: states restored,
    executables hot. The handoff report lands on :attr:`warm_report`.
    """

    def __init__(
        self,
        port: Optional[int] = None,
        host: str = "127.0.0.1",
        warm_target: Any = None,
        persist_dir: Optional[str] = None,
        snapshot_dir: Optional[str] = None,
        state_target: Any = None,
        fleet_target: Any = None,
    ) -> None:
        self._requested_port = _serve_stats.default_port() if port is None else int(port)
        self.host = host
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._warm_target = warm_target
        self._persist_dir = persist_dir
        self._snapshot_dir = snapshot_dir
        self._state_target = state_target
        self._fleet_target = fleet_target
        self.warm_report: Optional[dict] = None

    @property
    def url(self) -> str:
        if self.port is None:
            raise RuntimeError("sidecar not started")
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "MetricsSidecar":
        if self._server is not None:
            raise RuntimeError("sidecar already started")
        if self._warm_target is not None:
            # handoff BEFORE the socket binds: the first scrape a Prometheus
            # server lands already sees restored states and hot executables
            from torchmetrics_tpu.engine.persist import warm_start

            self.warm_report = warm_start(
                self._warm_target,
                directory=self._persist_dir,
                snapshot_dir=self._snapshot_dir,
            )
        server = ThreadingHTTPServer((self.host, self._requested_port), _ScrapeHandler)
        server.daemon_threads = True
        # the /state, /healthz, and /fleet/* handlers read these off the
        # server object (handler instances are per-request; the server is the
        # shared context) — a failed warm handoff must flip readiness, not
        # hide inside warm_report
        server.tm_state_target = self._state_target
        server.tm_fleet_target = self._fleet_target
        server.tm_warm_report = self.warm_report
        self._server = server
        self.port = server.server_address[1]
        self._thread = threading.Thread(
            target=server.serve_forever, name="tm-tpu-sidecar", daemon=True
        )
        self._thread.start()
        _diag.record("serve.sidecar.start", "sidecar", port=self.port)
        return self

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None
        self.port = None

    def __enter__(self) -> "MetricsSidecar":
        return self.start()

    def __exit__(self, *_: Any) -> None:
        self.stop()
