"""Multi-tenant slice registry: per-cohort metric views in fixed memory.

Serving evaluation for millions of users means per-segment metrics ("accuracy
for cohort 48213") without a Python object — let alone a compiled executable —
per segment. :class:`TenantSlices` holds ONE set of slotted state arrays
(``capacity`` rows per base state) and routes every update by tenant id **as
data**: the id enters the compiled graph as an array argument, the slot
lookup is an in-graph open-addressing probe, and the scatter-accumulate lands
in the same donated dispatch — so 10⁴ (or 10⁶) distinct tenants share ONE
executable signature with zero warm retraces.

Cardinality is bounded: when the table is full (or a probe chain is
exhausted), the update spills to a built-in heavy-hitter sketch
(``serve/sketch.py`` states, flat on this metric — no nested Metric), so the
spilled traffic keeps its volume accounting and its dominant tenants remain
identifiable in fixed memory. A dump row at index ``capacity`` absorbs
spilled contributions, which keeps :meth:`compute`'s GLOBAL aggregate exact
even past capacity.

Cross-rank semantics: the slotted arrays carry standard sum/max/min
reductions, so the packed sync folds them elementwise — exact whenever ranks
assign tenants to the same slots (same arrival order, or a pre-warmed table);
the spill sketch folds exactly via the ``hh-ids`` packed role. Per-tenant
VIEWS are host-side scrape reads (:meth:`tenant_value`) riding a sanctioned
transfer boundary — never part of the hot loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.serve import stats as _serve_stats
from torchmetrics_tpu.serve.sketch import (
    _CMS_SEEDS,
    _SEED_INDEX,
    _rank_zero_fold,
    canon_u32,
    canon_u32_host,
    hash_u32,
    hash_u32_host,
    merge_topk,
)
from torchmetrics_tpu.serve.snapshot import read_host
from torchmetrics_tpu.serve.window import (
    capture_np_defaults,
    check_streamable,
    extract_contribution,
    run_base_compute,
)
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

Array = jax.Array

__all__ = ["TenantSlices", "federated_rollup"]


class TenantSlices(Metric):
    """Fixed-capacity per-tenant metric slices over one template metric.

    Args:
        template: the per-slice metric definition (sum/max/min states only —
            the :func:`~torchmetrics_tpu.serve.window.check_streamable`
            algebra; ``MeanMetric``'s sum/count formulation works).
        capacity: tenant slots (power of two; default
            ``TORCHMETRICS_TPU_SERVE_CAPACITY`` → 4096).
        probes: linear-probe chain length per lookup (fixed, in-graph).
        spill_k / spill_depth / spill_width: heavy-hitter sketch geometry for
            the over-capacity spill.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SumMetric
        >>> from torchmetrics_tpu.serve import TenantSlices
        >>> slices = TenantSlices(SumMetric(nan_strategy=0.0), capacity=64)
        >>> slices.update(jnp.asarray(7), jnp.asarray(2.0))
        >>> slices.update(jnp.asarray(9), jnp.asarray(5.0))
        >>> slices.update(jnp.asarray(7), jnp.asarray(1.0))
        >>> float(slices.tenant_value(7)), float(slices.tenant_value(9))
        (3.0, 5.0)
    """

    _engine_traced_bodies = frozenset({"template"})
    full_state_update = True
    higher_is_better = None
    is_differentiable = False

    def __init__(
        self,
        template: Metric,
        capacity: Optional[int] = None,
        probes: int = 8,
        spill_k: int = 32,
        spill_depth: int = 4,
        spill_width: int = 2048,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self._slot_folds = check_streamable(template, type(self).__name__)
        if capacity is None:
            capacity = _serve_stats.default_capacity()
        if not (isinstance(capacity, int) and capacity >= 2 and (capacity & (capacity - 1)) == 0):
            raise TorchMetricsUserError(
                f"Expected argument `capacity` to be a power-of-two int >= 2 but got {capacity}"
            )
        if not (isinstance(probes, int) and probes >= 1):
            raise ValueError(f"Expected argument `probes` to be a positive int but got {probes}")
        self.template = template
        self.capacity = capacity
        self.probes = min(probes, capacity)
        self._base_keys = tuple(template._defaults)
        # slot table: -1 = empty; row `capacity` is the spill dump row, so an
        # exhausted probe chain scatters there instead of wrapping to row -1
        # ids and every counter ride the PR-8 count dtype (int64 under x64):
        # wide tenant ids store without truncation, and long-lived counters /
        # sketch cells cannot wrap at 2**31
        from torchmetrics_tpu.engine.numerics import count_dtype

        idt = count_dtype()
        self.add_state(
            "tenant_ids", default=jnp.full((capacity + 1,), -1, idt),
            dist_reduce_fx=_rank_zero_fold, spec={"dtype_policy": "count"},
        )
        self.add_state(
            "tenant_counts", default=jnp.zeros((capacity + 1,), idt),
            dist_reduce_fx="sum", spec={"dtype_policy": "count"},
        )
        for key in self._base_keys:
            default = template._defaults[key]
            slotted = jnp.broadcast_to(default, (capacity + 1,) + tuple(default.shape))
            self.add_state("seg_" + key, default=slotted, dist_reduce_fx=template._reductions[key])
        # spill accounting: exact volume + heavy-hitter sketch, with the joint
        # fold declared first-class in the specs (engine/statespec.py).
        # Registration order stays load-bearing: the grid precedes the hh pair,
        # which the packed hh-ids fold requires
        self.add_state(
            "spilled", default=jnp.zeros((), idt), dist_reduce_fx="sum",
            spec={"dtype_policy": "count"},
        )
        self.add_state(
            "spill_cms", default=jnp.zeros((spill_depth, spill_width), idt),
            dist_reduce_fx="sum", spec={"role": "hh-grid", "dtype_policy": "count"},
        )
        self.add_state(
            "spill_ids", default=jnp.full((spill_k,), -1, idt),
            dist_reduce_fx=_rank_zero_fold,
            spec={
                "role": "hh-ids",
                "hh": ("spill_cms", spill_k, spill_depth, spill_width),
                "dtype_policy": "count",
            },
        )
        self.add_state(
            "spill_counts", default=jnp.zeros((spill_k,), idt),
            dist_reduce_fx=_rank_zero_fold,
            spec={"role": "hh-counts", "dtype_policy": "count"},
        )
        self._spill_geom = (spill_k, spill_depth, spill_width)
        self._np_defaults = capture_np_defaults(template, self._base_keys)
        _serve_stats.register_tenancy(self)

    # ------------------------------------------------------------------ update

    def _lookup(self, table: Array, tid: Array) -> Array:
        """In-graph probe: slot index for ``tid``, or ``capacity`` (spill)."""
        h0 = hash_u32(canon_u32(tid), _SEED_INDEX)
        offsets = jnp.arange(self.probes, dtype=jnp.uint32)
        idx = ((h0 + offsets) & jnp.uint32(self.capacity - 1)).astype(jnp.int32)
        vals = table[idx]
        is_me = vals == tid
        is_empty = vals < 0
        found_slot = idx[jnp.argmax(is_me)]
        empty_slot = idx[jnp.argmax(is_empty)]
        return jnp.where(
            jnp.any(is_me),
            found_slot,
            jnp.where(jnp.any(is_empty), empty_slot, jnp.int32(self.capacity)),
        )

    def update(self, tenant_id: Any, *args: Any, **kwargs: Any) -> None:
        """Fold one tenant's batch into its slice — id is data, one graph.

        ``tenant_id`` is a non-negative integer scalar (array or Python int).
        A stream of distinct tenants reuses one compiled signature; spills
        past capacity land in the dump row + heavy-hitter sketch.
        """
        tid = jnp.asarray(tenant_id).astype(self.tenant_ids.dtype).reshape(())
        contrib = extract_contribution(
            self.template, self._np_defaults, self._base_keys,
            type(self).__name__, args, kwargs,
        )
        # negative ids collide with the -1 empty-slot sentinel (the probe
        # would "find" an empty cell and contaminate whichever tenant later
        # claims it) — route them straight to the spill/dump row instead
        slot = jnp.where(
            tid < 0, jnp.int32(self.capacity), self._lookup(self.tenant_ids, tid)
        )
        spilling = slot == self.capacity
        # claiming is idempotent for a found slot and harmless for the dump
        # row (its id cell is trash by definition)
        self.tenant_ids = self.tenant_ids.at[slot].set(tid)
        self.tenant_counts = self.tenant_counts.at[slot].add(1)
        for key in self._base_keys:
            seg = getattr(self, "seg_" + key)
            kind = self._slot_folds[key][0]
            ref = seg.at[slot]
            seg = (ref.add if kind == "sum" else ref.max if kind == "max" else ref.min)(contrib[key])
            setattr(self, "seg_" + key, seg)
        # spill path: weight-0 scatter when not spilling keeps the graph
        # branch-free (and the executable shared) for both cases
        self.spilled = self.spilled + spilling.astype(self.spilled.dtype)
        spill_k, spill_depth, spill_width = self._spill_geom
        cms = self.spill_cms
        w = spilling.astype(cms.dtype)
        u = canon_u32(tid).reshape((1,))
        for d in range(spill_depth):
            cidx = hash_u32(u, _CMS_SEEDS[d]) & jnp.uint32(spill_width - 1)
            cms = cms.at[d, cidx].add(w)
        self.spill_cms = cms
        candidate = jnp.where(spilling, tid, jnp.asarray(-1, tid.dtype)).reshape((1,))
        self.spill_ids, self.spill_counts = merge_topk(
            cms, jnp.concatenate([self.spill_ids, candidate]), spill_k, spill_depth, spill_width
        )

    # ------------------------------------------------------------------ compute

    def compute(self) -> Any:
        """GLOBAL aggregate across every tenant (dump row included — exact)."""
        folded = {}
        for key in self._base_keys:
            seg = getattr(self, "seg_" + key)
            kind = self._slot_folds[key][0]
            folded[key] = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[kind](seg, axis=0)
        return run_base_compute(self.template, folded)

    # ------------------------------------------------------------------ views

    def _host_slot(self, tenant_id: int, table: Optional[np.ndarray] = None) -> Optional[int]:
        if int(tenant_id) < 0:
            return None  # negative ids are spill-routed, never slotted
        if table is None:
            table = read_host(self, ("tenant_ids",))["tenant_ids"]
        # pure host arithmetic (bit-for-bit the device hash, pinned by test):
        # a device dispatch + readback here would trip the strict transfer
        # guard when a scrape lands mid-stream
        h0 = hash_u32_host(canon_u32_host(tenant_id), _SEED_INDEX)
        for j in range(self.probes):
            idx = (h0 + j) & (self.capacity - 1)
            if table[idx] == int(tenant_id):
                return idx
            if table[idx] < 0:
                return None
        return None

    def tenant_value(self, tenant_id: int) -> Optional[Any]:
        """This tenant's computed metric value, or None when never tracked.

        A scrape-path read: the table and slotted rows come to host through
        :func:`~torchmetrics_tpu.serve.snapshot.read_host` — the sanctioned,
        donation-race-retrying boundary — and the compute itself is the
        template's raw body over the slot's state row.
        """
        slot = self._host_slot(tenant_id)
        if slot is None:
            return None
        # one row per state crosses to host, not the capacity-sized tables
        # (the device-side index happens inside the same retried boundary)
        rows = read_host(self, tuple("seg_" + k for k in self._base_keys), index=slot)
        states = {key: jnp.asarray(rows["seg_" + key]) for key in self._base_keys}
        return run_base_compute(self.template, states)

    def tenant_updates(self, tenant_id: int) -> int:
        """Updates this tenant has received (0 when untracked/spilled).

        The per-slot counter behind this read is what makes slice traffic
        attributable at scrape time — `tenant_value` answers "what", this
        answers "over how many updates".
        """
        if int(tenant_id) < 0:
            return 0
        host = read_host(self, ("tenant_ids", "tenant_counts"))
        slot = self._host_slot(tenant_id, table=host["tenant_ids"])
        return 0 if slot is None else int(host["tenant_counts"][slot])

    def tenant_count(self) -> int:
        """Live tracked tenants (scrape-path host read, race-retried)."""
        table = read_host(self, ("tenant_ids",))["tenant_ids"]
        return int((table[: self.capacity] >= 0).sum())

    def spilled_count(self) -> int:
        """Updates that spilled past capacity (scrape-path host read)."""
        return int(read_host(self, ("spilled",))["spilled"])

    # tmlint: host-only — operates on the host dict read_host already fetched
    # through the sanctioned serve-scrape boundary
    def spill_report(self) -> Dict[str, Any]:
        """Spilled volume + the dominant spilled tenants from the sketch."""
        host = read_host(self, ("spill_ids", "spill_counts", "spilled"))
        ids, counts, spilled = host["spill_ids"], host["spill_counts"], int(host["spilled"])
        live = ids >= 0
        return {
            "spilled_updates": spilled,
            "heavy_hitters": [
                {"tenant": int(i), "estimate": int(c)}
                for i, c in zip(ids[live].tolist(), counts[live].tolist())
            ],
        }

def _host_cms_estimate(cms: np.ndarray, tenant_id: int, width: int) -> int:
    """Host-mirror count-min query (bit-for-bit the device hash chain)."""
    u = canon_u32_host(tenant_id)
    return int(
        min(int(cms[d][hash_u32_host(u, _CMS_SEEDS[d]) & (width - 1)]) for d in range(len(cms)))
    )


# tmlint: host-only — every device read below rides read_host's sanctioned
# serve-scrape boundary; the folds themselves are host numpy over those views
def federated_rollup(slices: Any) -> Dict[str, Any]:
    """Global per-tenant rollup across pods' :class:`TenantSlices` views.

    The federation fold for tenancy: given one :class:`TenantSlices` per pod
    (or, equivalently, per-pod clones restored from verified snapshots), fold
    the per-tenant slices **by tenant id** — NOT by slot, since each pod's
    probe table assigned its own slots — so tracked tenants stay *exact*
    across the fleet, with each state folded by its declared sum/max/min
    algebra and the update counters summed.

    Spilled traffic reconciles approximately but accountably: the spill
    volumes sum exactly, the count-min grids sum elementwise (the sketch's
    merge algebra), and the candidate heavy hitters — the union of every
    pod's tracked spill ids — are re-estimated against the MERGED grid with
    the host-mirror hash chain, so a tenant that spilled on several pods
    surfaces with its combined estimate even if no single pod ranked it.

    Returns ``{"tenants": {tid: {"value", "updates"}}, "spilled_updates",
    "heavy_hitters"}`` with deterministically ordered heavy hitters
    (estimate desc, id asc).
    """
    slices = list(slices)
    if not slices:
        raise TorchMetricsUserError(
            "federated_rollup needs at least one TenantSlices view to fold."
        )
    first = slices[0]
    base_keys = first._base_keys
    folds = first._slot_folds
    spill_k, spill_depth, spill_width = first._spill_geom
    for other in slices[1:]:
        if other._base_keys != base_keys or other._spill_geom != first._spill_geom:
            raise TorchMetricsUserError(
                "federated_rollup requires every pod's TenantSlices to share the"
                " template states and spill-sketch geometry — got mismatched"
                f" layouts ({base_keys} vs {other._base_keys})."
            )
    tenants: Dict[int, Dict[str, Any]] = {}
    spilled_total = 0
    cms_sum = np.zeros((spill_depth, spill_width), dtype=np.int64)
    candidates: set = set()
    for s in slices:
        host = read_host(
            s,
            ("tenant_ids", "tenant_counts", "spilled", "spill_cms", "spill_ids")
            + tuple("seg_" + k for k in base_keys),
        )
        table = host["tenant_ids"]
        counts = host["tenant_counts"]
        for slot in range(s.capacity):  # the dump row (index capacity) is spill
            tid = int(table[slot])
            if tid < 0:
                continue
            entry = tenants.get(tid)
            if entry is None:
                entry = tenants[tid] = {
                    "updates": 0,
                    "states": {key: None for key in base_keys},
                }
            entry["updates"] += int(counts[slot])
            for key in base_keys:
                row = np.asarray(host["seg_" + key][slot])
                prev = entry["states"][key]
                if prev is None:
                    entry["states"][key] = row
                else:
                    kind = folds[key][0]
                    entry["states"][key] = (
                        prev + row if kind == "sum"
                        else np.maximum(prev, row) if kind == "max"
                        else np.minimum(prev, row)
                    )
        spilled_total += int(host["spilled"])
        cms_sum += np.asarray(host["spill_cms"], dtype=np.int64)
        ids = np.asarray(host["spill_ids"])
        candidates.update(int(i) for i in ids[ids >= 0].tolist())
    out_tenants: Dict[int, Dict[str, Any]] = {}
    for tid in sorted(tenants):
        entry = tenants[tid]
        states = {key: jnp.asarray(v) for key, v in entry["states"].items()}
        out_tenants[tid] = {
            "value": run_base_compute(first.template, states),
            "updates": entry["updates"],
        }
    hh = [
        {"tenant": tid, "estimate": _host_cms_estimate(cms_sum, tid, spill_width)}
        for tid in sorted(candidates)
    ]
    hh.sort(key=lambda e: (-e["estimate"], e["tenant"]))
    return {
        "tenants": out_tenants,
        "spilled_updates": spilled_total,
        "heavy_hitters": hh[:spill_k],
    }
