"""Federated multi-pod aggregation plane: global metric merge across pods.

One pod's sidecar answers for one pod. A fleet-level question — "what is the
global accuracy / p99 / distinct-user count across every serving pod" — needs
the cross-pod fold the epoch engine already performs cross-rank, lifted one
tier up. This module is that tier:

- **Envelope** (:func:`pack_envelope` / :func:`parse_envelope`): one pod's
  metric states as a self-verifying ``.npz`` payload — layout-version stamp,
  order-independent payload CRC, a monotonic snapshot sequence number (the
  update-count watermark), list-state layout metadata, and the
  compensated-sum residuals so the two-sum chain re-anchors at the global
  tier. Built on :func:`~torchmetrics_tpu.serve.snapshot.take_snapshot`, so
  producing it never pauses the pod's update loop. Verification refuses to
  guess: a version or CRC mismatch raises the typed elastic-snapshot errors,
  never a silent partial ingest.
- **Aggregator** (:class:`FederationAggregator`): accepts envelopes by push
  (:meth:`~FederationAggregator.ingest`) or pulls them from pod sidecars'
  versioned ``/state`` endpoints (:meth:`~FederationAggregator.pull_round`,
  each fetch bounded by :func:`~torchmetrics_tpu.parallel.resilience.
  bounded_pull` under the resilience policy). The global value is the fold of
  the **latest verified snapshot per pod** — a returning pod *replaces* its
  slot, so rejoin can never double-count; a stale sequence number is rejected
  at the watermark (``federation.stale``).
- **Fold** — the existing packed-sync machinery, re-used verbatim: a
  :class:`~torchmetrics_tpu.parallel.packing.PackedSyncPlan` built over
  template clones maps each pod to a "rank", ``pack_from`` packs each
  verified snapshot into the per-(role, dtype) buffers, and one jitted
  ``make_fold`` executable — cached per (membership, plan signature) — folds
  the stacked buffers. Every StateSpec role keeps its cross-rank semantics at
  the cross-pod tier: sum/mean/max/min/cat, HLL register max, the
  heavy-hitter joint (grid, ids, counts) fold, and the compensated two-sum
  pairs re-anchored from the enveloped residuals. Pods are folded in
  **canonical pod-id order**, so the global result is byte-stable regardless
  of arrival order.
- **Degraded semantics** — PR-6 lifted to the aggregation tier: a pod that
  is unreachable, not yet ingested, or past the staleness bound is *excluded*
  from the fold (membership-keyed executable invalidation makes the exclusion
  structural), every exclusion is a counted ``federation.degraded`` event,
  and the fold still answers — degraded, never wrong, never hung.

The aggregator registers with ``serve/stats.py``, so a reused
:class:`~torchmetrics_tpu.serve.sidecar.MetricsSidecar`
(:meth:`FederationAggregator.serve`) exposes the global plane on the standard
Prometheus surface (``tm_tpu_federation_pods`` / ``_degraded_pods`` gauges
plus the ``tm_tpu_federation_*_total`` counters).
"""

from __future__ import annotations

import io
import json
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

from torchmetrics_tpu.diag import lineage as _lineage
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.diag.transfer_guard import transfer_allowed
from torchmetrics_tpu.engine.stats import EngineStats
from torchmetrics_tpu.parallel.elastic import SnapshotIntegrityError, SnapshotVersionError
from torchmetrics_tpu.parallel.resilience import (
    SyncFaultError,
    bounded_pull,
    resilience_context,
)
from torchmetrics_tpu.serve import stats as _serve_stats
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "FEDERATION_LAYOUT_VERSION",
    "FederationAggregator",
    "PodEnvelope",
    "pack_envelope",
    "parse_envelope",
]

#: envelope layout version — bumped on any change to the key scheme, the meta
#: JSON layout, or the CRC coverage. A mismatched version is a typed refusal
#: (:class:`~torchmetrics_tpu.parallel.elastic.SnapshotVersionError`), never a
#: guess at the layout.
FEDERATION_LAYOUT_VERSION = 1

#: HTTP header names the sidecar ``/state`` endpoint stamps (and the
#: aggregator cross-checks against the payload's own stamps)
VERSION_HEADER = "X-TM-Layout-Version"
CRC_HEADER = "X-TM-Payload-CRC"
SEQ_HEADER = "X-TM-Snapshot-Seq"

_RES_MARK = "__res__"  # key segment marking a compensated-sum residual entry


def _payload_crc(flat: Mapping[str, np.ndarray]) -> int:
    """Order-independent digest over every payload entry (elastic-shard style).

    Everything except the ``__crc__`` stamp itself is covered — including the
    ``__meta__`` layout JSON, the version, and the sequence number, so a
    tampered watermark or list layout is as loud as tampered state bytes.
    """
    crc = 0
    for key in sorted(flat):
        if key == "__crc__":
            continue
        arr = np.ascontiguousarray(flat[key])
        header = f"{key}|{arr.dtype}|{arr.shape}|".encode()
        crc = zlib.crc32(arr.tobytes(), zlib.crc32(header, crc))
    return crc & 0xFFFFFFFF


@dataclass
class PodEnvelope:
    """One pod's verified snapshot, parsed back into fold-ready form."""

    states: Dict[str, Dict[str, Any]]  # {owner: {attr: array-or-list}}
    residuals: Dict[str, Dict[str, Any]]  # {owner: {attr: residual array}}
    seq: int  # monotonic snapshot sequence (update-count watermark)
    update_counts: Dict[str, int] = field(default_factory=dict)


def _as_metric_map(target: Any) -> Dict[str, Any]:
    from torchmetrics_tpu.metric import Metric

    if isinstance(target, Metric):
        return {"metric": target}
    return dict(target)


def pack_envelope(metrics: Any, seq: Optional[int] = None) -> Tuple[bytes, Dict[str, str]]:
    """Serialize one pod's metric states into a self-verifying envelope.

    ``metrics`` is a Metric or an ``{owner: Metric}`` dict (owner keys must
    match the aggregator's template keys). Each metric is snapshotted with
    :func:`~torchmetrics_tpu.serve.snapshot.take_snapshot` — the pause-free
    consistency protocol — so the envelope is always a watermark-consistent
    cut, produced while the pod's update loop keeps dispatching.

    Returns ``(payload_bytes, headers)`` where ``headers`` carries the
    version/CRC/seq stamps for the HTTP ``/state`` surface. ``seq`` defaults
    to the summed update counts — monotonic per pod, which is all the
    aggregator's watermark dedupe needs.
    """
    from torchmetrics_tpu.serve.snapshot import take_snapshot

    metric_map = _as_metric_map(metrics)
    flat: Dict[str, np.ndarray] = {}
    meta: Dict[str, Any] = {"owners": {}}
    total_updates = 0
    provenance_rows = []
    for owner in sorted(metric_map):
        snap = take_snapshot(metric_map[owner])
        total_updates += snap.update_count
        if snap.provenance:
            provenance_rows.append(snap.provenance)
        attrs_meta: Dict[str, Any] = {}
        # the npz write below is the actual device->host materialization of
        # the snapshot copies — the sanctioned aggregation-tier boundary
        with transfer_allowed("federation-ingest"):
            for attr, value in snap.state.items():
                if isinstance(value, list):
                    attrs_meta[attr] = {"list": True, "n": len(value)}
                    for i, elem in enumerate(value):
                        flat[f"{owner}::{attr}::{i}"] = np.asarray(elem)
                else:
                    attrs_meta[attr] = {"list": False, "n": 1}
                    flat[f"{owner}::{attr}"] = np.asarray(value)
            residuals = snap.extras.get("_comp_residuals") or {}
            for attr, res in residuals.items():
                flat[f"{owner}::{_RES_MARK}::{attr}"] = np.asarray(res)
        meta["owners"][owner] = {
            "attrs": attrs_meta,
            "update_count": snap.update_count,
            "residuals": sorted(residuals),
        }
    seq = total_updates if seq is None else int(seq)
    flat["__meta__"] = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8
    ).copy()
    flat["__federation_version__"] = np.int64(FEDERATION_LAYOUT_VERSION)
    flat["__seq__"] = np.int64(seq)
    crc = _payload_crc(flat)
    flat["__crc__"] = np.uint32(crc)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    headers = {
        VERSION_HEADER: str(FEDERATION_LAYOUT_VERSION),
        CRC_HEADER: f"{crc:#010x}",
        SEQ_HEADER: str(seq),
    }
    if provenance_rows:
        # per-owner watermarks ride the envelope out-of-band: an aggregator
        # (or a human with curl -I) can audit what the payload covers without
        # parsing the npz
        headers[_lineage.LINEAGE_HEADER] = _lineage.encode_lineage_header(provenance_rows)
    return buf.getvalue(), headers


# tmlint: host-only — the payload is wire bytes; no device buffer reaches this
def parse_envelope(data: bytes, headers: Optional[Mapping[str, str]] = None) -> PodEnvelope:
    """Verify an envelope (version, CRC, header cross-check) and parse it.

    Refuses to guess: unreadable payloads and CRC mismatches raise
    :class:`~torchmetrics_tpu.parallel.elastic.SnapshotIntegrityError`, a
    layout-version mismatch raises
    :class:`~torchmetrics_tpu.parallel.elastic.SnapshotVersionError` — the
    same typed contract the elastic restore path enforces on disk shards.
    """
    if headers:
        raw_version = headers.get(VERSION_HEADER)
        if raw_version is not None and int(raw_version) != FEDERATION_LAYOUT_VERSION:
            raise SnapshotVersionError(
                f"pod snapshot advertises layout version {raw_version}, this build reads"
                f" {FEDERATION_LAYOUT_VERSION} — refusing to guess at the layout"
            )
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as npz:
            flat = {k: np.asarray(npz[k]) for k in npz.files}
    except Exception as err:  # noqa: BLE001 — unreadable IS the corruption signal
        raise SnapshotIntegrityError(f"pod snapshot payload is unreadable: {err}") from err
    for key in ("__federation_version__", "__seq__", "__crc__", "__meta__"):
        if key not in flat:
            raise SnapshotIntegrityError(
                f"pod snapshot payload lacks the {key} stamp — not a federation envelope"
            )
    version = int(flat["__federation_version__"])
    if version != FEDERATION_LAYOUT_VERSION:
        raise SnapshotVersionError(
            f"pod snapshot has layout version {version}, this build reads"
            f" {FEDERATION_LAYOUT_VERSION} — refusing to guess at the layout"
        )
    expected = int(flat["__crc__"])
    actual = _payload_crc(flat)
    if actual != expected:
        raise SnapshotIntegrityError(
            f"pod snapshot failed its integrity check (crc {actual:#010x} !="
            f" stamped {expected:#010x}) — the payload is corrupt"
        )
    if headers:
        raw_crc = headers.get(CRC_HEADER)
        if raw_crc is not None and int(raw_crc, 0) != expected:
            raise SnapshotIntegrityError(
                f"pod snapshot header CRC {raw_crc} disagrees with the payload stamp"
                f" {expected:#010x} — the transport delivered a different payload"
            )
    meta = json.loads(bytes(flat["__meta__"]).decode())
    states: Dict[str, Dict[str, Any]] = {}
    residuals: Dict[str, Dict[str, Any]] = {}
    update_counts: Dict[str, int] = {}
    for owner, owner_meta in meta["owners"].items():
        owner_states: Dict[str, Any] = {}
        for attr, attr_meta in owner_meta["attrs"].items():
            if attr_meta["list"]:
                owner_states[attr] = [
                    flat[f"{owner}::{attr}::{i}"] for i in range(attr_meta["n"])
                ]
            else:
                owner_states[attr] = flat[f"{owner}::{attr}"]
        states[owner] = owner_states
        if owner_meta["residuals"]:
            residuals[owner] = {
                attr: flat[f"{owner}::{_RES_MARK}::{attr}"]
                for attr in owner_meta["residuals"]
            }
        update_counts[owner] = int(owner_meta["update_count"])
    return PodEnvelope(
        states=states,
        residuals=residuals,
        seq=int(flat["__seq__"]),
        update_counts=update_counts,
    )


@dataclass
class _PodSlot:
    """The latest verified snapshot held for one pod."""

    envelope: PodEnvelope
    ts: float  # time.monotonic() at ingest — drives the staleness watermark


def _http_fetcher(url: str, timeout_s: Optional[float]) -> Callable[[], Tuple[bytes, Dict[str, str]]]:
    def fetch() -> Tuple[bytes, Dict[str, str]]:
        import urllib.request

        with urllib.request.urlopen(url, timeout=timeout_s or 10.0) as resp:
            return resp.read(), dict(resp.headers.items())

    return fetch


class FederationAggregator:
    """Fold N pods' verified snapshots into one global metric plane.

    Args:
        template: a Metric or ``{owner: Metric}`` dict DEFINING the states to
            federate — the same definitions every pod runs. The template's own
            state is never read; per-fold clones carry the pod snapshots.
        pods: ``{pod_id: source}`` where source is a ``/state`` URL (string)
            or a zero-arg callable returning ``bytes`` or ``(bytes, headers)``
            — callables let tests and benches emulate pods without sockets.
        staleness_s: snapshots older than this (since ingest) are excluded
            from folds as degraded members. Default:
            ``TORCHMETRICS_TPU_FEDERATION_STALENESS_S`` (unset = no bound).
        timeout_ms: per-pull deadline for :meth:`pull_round`. Default:
            ``TORCHMETRICS_TPU_FEDERATION_TIMEOUT_MS`` (unset = no deadline).
        retries: bounded-pull retry budget. Default:
            ``TORCHMETRICS_TPU_FEDERATION_RETRIES`` (2).

    The global value is byte-stable for a fixed membership regardless of pod
    arrival order: members are canonically ordered by pod id before packing,
    and one jitted fold executable — cached per (membership, plan signature)
    — serves every fold over that membership.
    """

    def __init__(
        self,
        template: Any,
        pods: Optional[Mapping[str, Any]] = None,
        staleness_s: Optional[float] = None,
        timeout_ms: Optional[float] = None,
        retries: Optional[int] = None,
    ) -> None:
        from torchmetrics_tpu.parallel.resilience import _env_float

        self.template = _as_metric_map(template)
        if not self.template:
            raise TorchMetricsUserError(
                "FederationAggregator needs at least one template metric — an empty"
                " template has no states to federate."
            )
        self.pods: Dict[str, Any] = dict(pods or {})
        self.staleness_s = (
            _env_float("TORCHMETRICS_TPU_FEDERATION_STALENESS_S")
            if staleness_s is None
            else float(staleness_s)
        )
        self.timeout_ms = (
            _env_float("TORCHMETRICS_TPU_FEDERATION_TIMEOUT_MS")
            if timeout_ms is None
            else float(timeout_ms)
        )
        self.retries = _serve_stats.federation_retries() if retries is None else int(retries)
        self.stats = EngineStats("federation")
        self._lock = threading.Lock()
        self._slots: Dict[str, _PodSlot] = {}  # guarded-by: _lock
        self._watermarks: Dict[str, int] = {}  # guarded-by: _lock
        self._excluded: set = set()  # guarded-by: _lock — pods out of the last fold
        self._last_pods = 0  # guarded-by: _lock — membership of the last fold
        self._last_degraded = 0  # guarded-by: _lock
        self._fold_cache: Dict[Tuple, Any] = {}  # guarded-by: _lock — jitted folds
        self._scratch: Dict[str, Any] = {}  # guarded-by: _lock — compute clones
        #: coverage stamp of the last fold (diag/lineage.py ``note_coverage``
        #: form) — who the global value includes, who it excludes, and why
        self.last_coverage: Optional[Dict[str, Any]] = None
        _serve_stats.register_federation(self)

    # ------------------------------------------------------------------ ingest

    def ingest(self, pod_id: str, data: bytes, headers: Optional[Mapping[str, str]] = None) -> bool:
        """Verify and accept one pod envelope (push path).

        Returns True when the snapshot advanced the pod's watermark; False
        when the watermark dedupe rejected it as stale (a replayed or
        out-of-order snapshot — counted, evented, never folded twice).
        """
        envelope = parse_envelope(data, headers)
        missing = sorted(set(self.template) - set(envelope.states))
        if missing:
            # folding an absent owner would silently poison the global value —
            # a definition mismatch between pod and aggregator is a user error
            raise TorchMetricsUserError(
                f"pod {pod_id!r} snapshot lacks states for template owner(s)"
                f" {missing} (envelope holds {sorted(envelope.states)}) — the pod"
                " and the aggregator must run the same metric definitions under"
                " the same owner keys."
            )
        with self._lock:
            prev = self._watermarks.get(pod_id)
            if prev is not None and envelope.seq <= prev:
                self.stats.federation_stale_skips += 1
                _diag.record(
                    "federation.stale", "federation",
                    pod=pod_id, seq=envelope.seq, watermark=prev,
                )
                return False
            rejoined = pod_id in self._excluded
            self._excluded.discard(pod_id)
            self._slots[pod_id] = _PodSlot(envelope=envelope, ts=time.monotonic())
            self._watermarks[pod_id] = envelope.seq
            self.stats.federation_ingests += 1
        if rejoined:
            # the pod REPLACES its slot, so re-admission cannot double-count —
            # but it is a membership change worth narrating
            _diag.record("federation.rejoin", "federation", pod=pod_id, seq=envelope.seq)
        _diag.record(
            "federation.ingest", "federation",
            pod=pod_id, seq=envelope.seq, bytes=len(data),
        )
        return True

    def pull_round(self) -> Dict[str, bool]:
        """Pull every configured pod's ``/state`` once (bounded, classified).

        Each fetch runs through :func:`~torchmetrics_tpu.parallel.resilience.
        bounded_pull` — deadline watchdog, retry/backoff, typed fault
        classification, and the fault-injection hook (pod-churn chaos tests
        plant at this exact boundary). A pod whose pull terminally fails is
        excluded (``federation.degraded``) until it is ingested again; the
        round never raises for a single lost pod.

        Returns ``{pod_id: ingested}`` (False = unreachable or stale).
        """
        pod_ids = sorted(self.pods)
        member_idx = {pid: i for i, pid in enumerate(pod_ids)}
        results: Dict[str, bool] = {}
        timeout_s = self.timeout_ms / 1e3 if self.timeout_ms else None
        with resilience_context(deadline_ms=self.timeout_ms, retries=self.retries):
            for pid in pod_ids:
                source = self.pods[pid]
                fetch = source if callable(source) else _http_fetcher(source, timeout_s)
                try:
                    out = bounded_pull(
                        fetch,
                        label=f"federation-pull:{pid}",
                        rank=member_idx[pid],
                        # a pull involves ONLY its target pod — rank-scoped
                        # fault injection (pod-churn chaos) hits exactly that
                        # pod's fetch, not the whole round
                        members=[member_idx[pid]],
                    )
                except SyncFaultError as exc:
                    with self._lock:
                        self._excluded.add(pid)
                    _diag.record(
                        "federation.degraded", "federation",
                        pod=pid, reason=type(exc).__name__, attempts=exc.attempts,
                    )
                    results[pid] = False
                    continue
                data, headers = out if isinstance(out, tuple) else (out, None)
                results[pid] = self.ingest(pid, data, headers)
        return results

    # ------------------------------------------------------------------ fold

    def _fresh_membership(self) -> Tuple[Dict[str, _PodSlot], List[str], List[Tuple[str, str]]]:
        now = time.monotonic()
        with self._lock:
            slots = dict(self._slots)
            known = sorted(set(self.pods) | set(slots))
        fresh: Dict[str, _PodSlot] = {}
        for pid in sorted(slots):
            slot = slots[pid]
            if self.staleness_s is not None and now - slot.ts > self.staleness_s:
                continue
            fresh[pid] = slot
        members = sorted(fresh)
        excluded = [
            (pid, "stale" if pid in slots else "missing") for pid in known if pid not in fresh
        ]
        return fresh, members, excluded

    def _build_plan(self, members: List[str], fresh: Dict[str, _PodSlot]) -> Any:
        from torchmetrics_tpu.parallel.packing import PackedSyncPlan

        # representative snapshot for the plan skeleton: list-typed states
        # (cat lists) must be NONEMPTY on the building "rank" for their
        # element dtype — hence the buffer layout — to be knowable, so prefer
        # the pod holding the most populated lists (deterministic tie-break by
        # canonical order)
        def _list_score(pid: str) -> int:
            return sum(
                1
                for owner_states in fresh[pid].envelope.states.values()
                for value in owner_states.values()
                if isinstance(value, list) and value
            )

        rep = max(members, key=lambda pid: (_list_score(pid), -members.index(pid)))
        rep_states = fresh[rep].envelope.states
        clones: List[Tuple[str, Any]] = []
        import jax.numpy as jnp

        with transfer_allowed("federation-ingest"):
            for owner in sorted(self.template):
                clone = self.template[owner].clone()
                clone.sync_on_compute = False
                clone._to_sync = False
                clone.compute_with_cache = False
                for attr, value in rep_states.get(owner, {}).items():
                    if attr in clone._defaults:
                        staged = (
                            [jnp.asarray(e) for e in value]
                            if isinstance(value, list)
                            else jnp.asarray(value)
                        )
                        object.__setattr__(clone, attr, staged)
                clones.append((owner, clone))
        plan = PackedSyncPlan(clones, world_size=len(members))
        # the aggregation tier disables the metadata riders: there is no
        # cross-rank barrier to timestamp and the divergence audit's
        # rank-invariance contract does not apply to independent pods
        plan.audit = False
        plan.timeline = False
        metas = [plan.metadata_from_state(fresh[pid].envelope.states) for pid in members]
        world_meta = None if metas[0] is None else np.stack(metas)
        plan.finalize(world_meta)
        return plan

    def fold(self) -> Dict[str, Dict[str, Any]]:
        """One global fold over the fresh membership → ``{owner: {attr: value}}``.

        Degraded is a first-class outcome: excluded pods (stale, unreachable,
        never ingested) are dropped from the membership, counted, and evented
        — the fold still answers over who is left. No verified snapshot at
        all raises :class:`~torchmetrics_tpu.utilities.exceptions.
        TorchMetricsUserError` (nothing to answer with is an error, not a 0).
        """
        import jax
        import jax.numpy as jnp

        fresh, members, excluded = self._fresh_membership()
        if not members:
            raise TorchMetricsUserError(
                "Federation fold has no verified pod snapshot to fold — ingest or"
                " pull at least one pod before asking for a global value."
            )
        plan = self._build_plan(members, fresh)
        # envelope arrays are host numpy; staging them into the fold's device
        # buffers is the sanctioned aggregation-tier transfer
        with transfer_allowed("federation-ingest"):
            packed = [
                plan.pack_from(fresh[pid].envelope.states, fresh[pid].envelope.residuals)
                for pid in members
            ]
            gathered = {k: jnp.stack([p[k] for p in packed]) for k in packed[0]}
        cache_key = (tuple(members), plan.signature())
        with self._lock:
            fold_fn = self._fold_cache.get(cache_key)
            if fold_fn is None:
                # membership-keyed invalidation is structural: the pod-id
                # tuple is part of the key, so a degraded fold can never be
                # served by the full-membership executable (or vice versa)
                fold_fn = self._fold_cache[cache_key] = jax.jit(plan.make_fold())
        result = fold_fn(gathered)
        with self._lock:
            self._excluded.update(pid for pid, _ in excluded)
            self._last_pods = len(members)
            self._last_degraded = len(excluded)
            self.stats.federation_folds += 1
            if excluded:
                self.stats.federation_degraded_folds += 1
        for pid, reason in excluded:
            _diag.record("federation.degraded", "federation", pod=pid, reason=reason)
        # coverage attestation: the stamp names exactly who this global value
        # folded (pod ids + their snapshot seqs) and who it excluded and why —
        # a degraded 3/4-pod fold is visibly a 3/4-pod value, never a silent 4/4
        stamp = _lineage.note_coverage(
            "federation",
            members,
            seqs={pid: fresh[pid].envelope.seq for pid in members},
            excluded=excluded,
        )
        self.last_coverage = stamp
        _diag.record(
            "federation.fold", "federation",
            pods=len(members), degraded=len(excluded), members=",".join(members),
        )
        return result

    def compute_global(self) -> Any:
        """Fold, then ``compute()`` each owner on its scratch clone.

        Returns the single value for a single-Metric template, else
        ``{owner: value}``. The template metrics themselves are never touched
        — the folded states install into cached compute-only clones (the
        snapshot-compute discipline at the aggregation tier).
        """
        folded = self.fold()
        with self._lock:
            update_counts = {
                pid: slot.envelope.update_counts for pid, slot in self._slots.items()
            }
        values: Dict[str, Any] = {}
        for owner in sorted(self.template):
            with self._lock:
                scratch = self._scratch.get(owner)
                if scratch is None:
                    scratch = self.template[owner].clone()
                    scratch.sync_on_compute = False
                    scratch._to_sync = False
                    scratch.compute_with_cache = False
                    self._scratch[owner] = scratch
            total_updates = sum(c.get(owner, 0) for c in update_counts.values())
            prior = dict(scratch.__dict__)
            try:
                for attr, value in folded.get(owner, {}).items():
                    if attr in scratch._defaults:
                        object.__setattr__(scratch, attr, value)
                object.__setattr__(scratch, "_update_count", max(total_updates, 1))
                object.__setattr__(scratch, "_computed", None)
                values[owner] = scratch._raw_compute()
            finally:
                scratch.__dict__.clear()
                scratch.__dict__.update(prior)
        return values["metric"] if set(self.template) == {"metric"} else values

    # ------------------------------------------------------------------ views

    def federation_state(self) -> Dict[str, int]:
        """The telemetry gauge row (``serve/stats.py`` registry contract)."""
        with self._lock:
            if self._last_pods:
                return {"pods": self._last_pods, "degraded_pods": self._last_degraded}
            return {"pods": len(self._slots), "degraded_pods": len(self._excluded)}

    def serve(self, port: Optional[int] = None, host: str = "127.0.0.1") -> Any:
        """Expose the global plane on a reused sidecar (started; caller stops).

        The standard :class:`~torchmetrics_tpu.serve.sidecar.MetricsSidecar`
        already exports everything this aggregator registers — the federation
        gauges and counters ride the same ``/metrics`` Prometheus surface a
        pod's sidecar serves.
        """
        from torchmetrics_tpu.serve.sidecar import MetricsSidecar

        return MetricsSidecar(port=port, host=host).start()
