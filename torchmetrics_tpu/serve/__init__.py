"""Streaming / serving subsystem: continuous-traffic evaluation (ROADMAP item 2).

The engine (PRs 1–2) makes per-epoch evaluation fast; this package makes it
SERVABLE — unbounded streams, millions of user slices, scrape-anytime
semantics, all without host transfers in the hot loop:

- :mod:`~torchmetrics_tpu.serve.window` — :class:`WindowedMetric` (ring of
  partial states, advance/evict/fold in one donated dispatch) and
  :class:`DecayedMetric` (EMA states) over any sum/max/min-state base metric;
- :mod:`~torchmetrics_tpu.serve.sketch` — :class:`CardinalitySketch`
  (HLL-style distinct counting, max-merge) and :class:`HeavyHitters`
  (count-min + in-graph top-k) as fixed-memory first-class metric states;
- :mod:`~torchmetrics_tpu.serve.quantile` — :class:`KLLSketch`: mergeable
  deterministic quantile sketch (fixed compactor levels, in-graph update,
  proven rank-error bound) seeded from the ``diag/hist.py`` bucket scheme;
- :mod:`~torchmetrics_tpu.serve.tenancy` — :class:`TenantSlices`: bounded
  per-tenant slices sharing ONE executable (tenant id is data), spilling to
  the heavy-hitter sketch past capacity; :func:`federated_rollup` folds
  per-pod views into exact global per-tenant values;
- :mod:`~torchmetrics_tpu.serve.snapshot` — :func:`snapshot_compute`:
  ``compute()`` on a shielded state copy while updates continue;
- :mod:`~torchmetrics_tpu.serve.sidecar` — :class:`MetricsSidecar`: the PR-4
  Prometheus/JSONL exporters behind a threaded scrape endpoint, plus the
  versioned ``/state`` snapshot-envelope surface;
- :mod:`~torchmetrics_tpu.serve.federation` —
  :class:`FederationAggregator`: the multi-pod aggregation plane — verified
  envelope ingest/pull, canonical-order global folds through the packed-sync
  machinery, degraded semantics at pod loss;
- :mod:`~torchmetrics_tpu.serve.fleet` — :class:`FleetTelemetry`: the fleet
  observability plane — every pod's counters/histograms/sentinels pulled as
  verified ``/telemetry.bin`` envelopes, merged bound-preservingly
  (``merge_hists``), exposed as pod-labeled + ``tm_tpu_fleet_*`` exposition
  and fleet-wide SLO evaluation (``diag/slo.py``).

See ``docs/pages/serving.md`` for semantics, error bounds, and knobs.
"""

from torchmetrics_tpu.serve.federation import FederationAggregator, pack_envelope, parse_envelope
from torchmetrics_tpu.serve.fleet import FleetTelemetry, pack_telemetry, parse_telemetry
from torchmetrics_tpu.serve.quantile import KLLSketch
from torchmetrics_tpu.serve.sidecar import MetricsSidecar
from torchmetrics_tpu.serve.sketch import CardinalitySketch, HeavyHitters
from torchmetrics_tpu.serve.snapshot import StateSnapshot, snapshot_compute, take_snapshot
from torchmetrics_tpu.serve.stats import reset_serve_stats, serve_state
from torchmetrics_tpu.serve.tenancy import TenantSlices, federated_rollup
from torchmetrics_tpu.serve.window import DecayedMetric, WindowedMetric

__all__ = [
    "CardinalitySketch",
    "DecayedMetric",
    "FederationAggregator",
    "FleetTelemetry",
    "HeavyHitters",
    "KLLSketch",
    "MetricsSidecar",
    "StateSnapshot",
    "TenantSlices",
    "WindowedMetric",
    "federated_rollup",
    "pack_envelope",
    "pack_telemetry",
    "parse_envelope",
    "parse_telemetry",
    "reset_serve_stats",
    "serve_state",
    "snapshot_compute",
    "take_snapshot",
]
