"""Fixed-memory sketch states as first-class ``Metric`` states.

Serving millions of user slices needs answers to "how many distinct X?" and
"which X dominate?" in memory that does NOT grow with the stream. Two classic
sketches become ordinary metric states here, so they ride the engine's donated
compiled updates and the packed epoch sync like any accumulator:

- :class:`CardinalitySketch` — HyperLogLog-style distinct counting. State is a
  fixed vector of int32 registers; the cross-rank merge is an **elementwise
  max**, which is exactly the existing ``dist_reduce_fx="max"`` packed-spec
  role — no new sync machinery, and merging rank registers is bit-identical to
  hashing the union stream on one rank (the hash is seed-deterministic).
- :class:`HeavyHitters` — count-min sketch + an in-graph top-k candidate list.
  The count-min grid folds cross-rank by **elementwise sum** (the existing
  reduce role; CMS(A) + CMS(B) == CMS(A ∪ B) exactly), while the
  ``(ids, counts)`` top-k pair needs a JOINT fold against the merged grid —
  registered as the ``hh-ids``/``hh-counts`` :class:`StateSpec` roles
  (``engine/statespec.py``) that ``parallel/packing.py`` resolves; membership
  is a function of the metric definition alone, so rank layouts cannot
  desynchronize. (The deprecated ``_hh_fold_info`` attribute mirror is gone —
  out-of-tree metrics declare the pair through ``add_state(spec=...)``, or
  keep setting the attribute and ride the counted legacy-derivation fallback.)

All hashing stays in uint32 space (murmur3 finalizer) so the sketches behave
identically with and without the x64 flag; ids must be non-negative (−1 is the
empty-slot sentinel in the top-k list).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

Array = jax.Array

__all__ = ["CardinalitySketch", "HeavyHitters", "cms_query", "hash_u32", "canon_u32"]

#: independent seed constants (odd, high-entropy) for the hash family
_SEED_INDEX = 0x9E3779B9
_SEED_RHO = 0x85EBCA6B
_CMS_SEEDS = (0xC2B2AE35, 0x27D4EB2F, 0x165667B1, 0xD3A2646C, 0xFD7046C5, 0xB55A4F09)


def hash_u32(x: Array, seed: int) -> Array:
    """Murmur3 finalizer over uint32 lanes — a seeded, well-mixed 32-bit hash."""
    x = x.astype(jnp.uint32) ^ jnp.uint32(seed)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash_u32_host(value: int, seed: int) -> int:
    """:func:`hash_u32` for one Python int, pure host arithmetic.

    Scrape-path slot resolution (``TenantSlices._host_slot``) must not
    dispatch a device op per lookup — and more importantly must not read a
    device result back outside a sanctioned boundary, which would raise under
    the strict transfer guard mid-stream. Bit-for-bit the device hash
    (pinned by test).
    """
    x = (int(value) ^ seed) & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def canon_u32_host(value: int) -> int:
    """:func:`canon_u32` for one non-negative Python int (host mirror)."""
    value = int(value)
    lo = value & 0xFFFFFFFF
    hi = (value >> 32) & 0xFFFFFFFF
    return lo if hi == 0 else lo ^ hash_u32_host(hi, _SEED_INDEX)


def canon_u32(ids: Any) -> Array:
    """Canonicalize an id array to uint32 hash input, dtype-stably.

    64-bit integer ids fold their high word in ONLY when it is nonzero (so
    ids past 2**32 don't collide wholesale, while any non-negative id that
    fits 32 bits hashes identically whether it arrives as int32 or int64 —
    i.e. with or without the x64 flag; an unconditional fold would XOR
    ``hash(0)`` into every 64-bit id and put the same tenant in different
    registers per input dtype). Floats hash their float32 bit pattern.
    """
    ids = jnp.asarray(ids)
    if jnp.issubdtype(ids.dtype, jnp.floating):
        return jax.lax.bitcast_convert_type(ids.astype(jnp.float32), jnp.uint32)
    if jnp.dtype(ids.dtype).itemsize == 8:
        lo = (ids & 0xFFFFFFFF).astype(jnp.uint32)
        hi = (ids >> 32).astype(jnp.uint32)
        return jnp.where(hi == 0, lo, lo ^ hash_u32(hi, _SEED_INDEX))
    return ids.astype(jnp.uint32)


def cms_query(cms: Array, u32: Array, depth: int, width: int) -> Array:
    """Point-estimate counts for hashed ids: min over the depth rows."""
    est = None
    for d in range(depth):
        idx = hash_u32(u32, _CMS_SEEDS[d]) & jnp.uint32(width - 1)
        row = cms[d, idx]
        est = row if est is None else jnp.minimum(est, row)
    return est


def _rank_zero_fold(stacked: Array) -> Array:
    """Eager-sync fallback fold for the top-k pair: keep the local rank's list.

    The exact joint fold (union of candidates re-estimated against the merged
    count-min grid) only exists on the packed plan, where the merged grid is
    available in the same fold graph. The eager per-state path folds each
    state independently, so it keeps rank 0's list — approximate by design,
    documented in ``docs/pages/serving.md``.
    """
    return stacked[0]


class CardinalitySketch(Metric):
    """HyperLogLog-style distinct counter in ``2**p`` int32 registers.

    ``update(ids)`` hashes every id and scatter-maxes the leading-zero rank
    into its register; ``compute()`` returns the bias-corrected estimate with
    the linear-counting small-range correction. Standard error is
    ``1.04 / sqrt(2**p)`` (~2.3% at the default ``p=11`` — inside the ±3%
    serving bound at 10⁵ uniques).

    Cross-rank sync is the plain ``max`` reduce role: registers merged by
    elementwise max equal the registers of the union stream bit-for-bit.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.serve import CardinalitySketch
        >>> sketch = CardinalitySketch()
        >>> sketch.update(jnp.arange(1000))
        >>> bool(abs(float(sketch.compute()) - 1000) < 100)
        True
    """

    full_state_update = True
    higher_is_better = None
    is_differentiable = False

    def __init__(self, p: int = 11, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(p, int) and 4 <= p <= 18):
            raise ValueError(f"Expected argument `p` to be an int in [4, 18] but got {p}")
        self.p = p
        self.m = 1 << p
        self.add_state("registers", default=jnp.zeros((self.m,), jnp.int32), dist_reduce_fx="max")
        from torchmetrics_tpu.serve import stats as _serve_stats

        _serve_stats.register_sketch(self)

    def update(self, ids: Any) -> None:
        """Fold a batch of (non-negative integer or float) ids into the registers."""
        u = canon_u32(ids).ravel()
        idx = hash_u32(u, _SEED_INDEX) & jnp.uint32(self.m - 1)
        # rank of the first set bit of an independent hash: clz+1, so a zero
        # word reads as 33 (the standard "all bits zero" register ceiling)
        rho = (jax.lax.clz(hash_u32(u, _SEED_RHO)) + 1).astype(jnp.int32)
        self.registers = self.registers.at[idx].max(rho)

    def compute(self) -> Array:
        """Bias-corrected harmonic-mean estimate with small-range correction."""
        regs = self.registers.astype(jnp.float32)
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        raw = alpha * m * m / jnp.sum(jnp.exp2(-regs))
        zeros = jnp.sum(self.registers == 0).astype(jnp.float32)
        linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
        return jnp.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)

    def fill_ratio(self) -> float:
        """Fraction of touched registers — the scrape-side saturation gauge."""
        from torchmetrics_tpu.serve.snapshot import read_host

        regs = read_host(self, ("registers",))["registers"]
        return float((regs > 0).mean())


class HeavyHitters(Metric):
    """Count-min sketch + in-graph top-k heavy-hitter list, fixed memory.

    ``update(ids, weights=None)`` scatter-adds every id into the
    ``(depth, width)`` count-min grid, re-estimates the union of the current
    top-k candidates and the batch ids against the updated grid, dedupes
    in-graph (sort + run-boundary mask, all fixed shapes) and keeps the new
    top-k — one compiled graph, no host round-trip, ids as DATA (a stream of
    distinct ids reuses one executable).

    ``compute()`` returns ``(ids, counts)``; empty slots are ``-1`` / ``0``.
    Counts are CMS point estimates: one-sided overestimates with error
    ``<= e * N / width`` at probability ``1 - e^-depth``.

    Cross-rank sync: the grid sums (exact); the ``(ids, counts)`` pair folds
    jointly through the ``hh-ids``/``hh-counts`` roles its registered
    :class:`StateSpec`s declare (union of per-rank candidates re-estimated
    against the merged grid — identical to a single-rank pass whenever each
    true heavy hitter made some rank's local list).
    """

    full_state_update = True
    higher_is_better = None
    is_differentiable = False

    def __init__(self, k: int = 32, depth: int = 4, width: int = 2048, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(k, int) and k > 0):
            raise ValueError(f"Expected argument `k` to be a positive int but got {k}")
        if not (isinstance(depth, int) and 1 <= depth <= len(_CMS_SEEDS)):
            raise ValueError(f"Expected argument `depth` to be an int in [1, {len(_CMS_SEEDS)}] but got {depth}")
        if not (isinstance(width, int) and width >= 2 and (width & (width - 1)) == 0):
            raise ValueError(f"Expected argument `width` to be a power-of-two int >= 2 but got {width}")
        self.k = k
        self.depth = depth
        self.width = width
        # id/count dtype rides the PR-8 count contract (int64 under x64):
        # 64-bit ids store natively instead of silently truncating to int32,
        # and the grid cells cannot wrap negative (a wrapped cell would make
        # cms_query return a negative estimate and the heaviest hitter would
        # rank BELOW empty slots). Without x64 no wider device integer exists
        # — and no 64-bit id can enter either. Ids and counts share one
        # dtype, so the top-k pair still rides a single gather buffer.
        from torchmetrics_tpu.engine.numerics import count_dtype

        idt = count_dtype()
        # registration ORDER is load-bearing: the packed fold estimates the
        # top-k candidates against the merged grid, so the grid's spec must
        # precede the hh pair in the plan (parallel/packing.py enforces it)
        # first-class roles (engine/statespec.py): the grid + (ids, counts)
        # pair declare the joint heavy-hitter fold directly in their specs —
        # membership is a function of the metric DEFINITION (not live values),
        # so every rank builds the same plan layout unconditionally
        self.add_state(
            "cms", default=jnp.zeros((depth, width), idt), dist_reduce_fx="sum",
            spec={"role": "hh-grid", "dtype_policy": "count"},
        )
        self.add_state(
            "hh_ids", default=jnp.full((k,), -1, idt), dist_reduce_fx=_rank_zero_fold,
            spec={"role": "hh-ids", "hh": ("cms", k, depth, width), "dtype_policy": "count"},
        )
        self.add_state(
            "hh_counts", default=jnp.zeros((k,), idt), dist_reduce_fx=_rank_zero_fold,
            spec={"role": "hh-counts", "dtype_policy": "count"},
        )
        from torchmetrics_tpu.serve import stats as _serve_stats

        _serve_stats.register_sketch(self)

    def update(self, ids: Any, weights: Optional[Any] = None) -> None:
        """Fold a batch of non-negative integer ids (optionally weighted) in.

        The grid hashes the SAME canonicalization the top-k stores (the
        id-state dtype — int64 under x64, so wide ids never truncate; without
        x64 no 64-bit input can exist), keeping CMS cells and re-estimation
        queries aligned.
        """
        id_dtype = self.hh_ids.dtype
        ids_cast = jnp.asarray(ids).ravel().astype(id_dtype)
        u = canon_u32(ids_cast)
        w = (
            jnp.ones(ids_cast.shape, self.cms.dtype)
            if weights is None
            else jnp.asarray(weights).ravel().astype(self.cms.dtype)
        )
        cms = self.cms
        for d in range(self.depth):
            idx = hash_u32(u, _CMS_SEEDS[d]) & jnp.uint32(self.width - 1)
            cms = cms.at[d, idx].add(w)
        self.cms = cms
        self.hh_ids, self.hh_counts = merge_topk(
            cms, jnp.concatenate([self.hh_ids, ids_cast]), self.k, self.depth, self.width
        )

    def compute(self) -> Tuple[Array, Array]:
        """The current top-k as ``(ids, counts)`` (empty slots ``-1`` / ``0``)."""
        return self.hh_ids, self.hh_counts

    def fill_ratio(self) -> float:
        """Fraction of touched count-min cells — the scrape-side saturation gauge."""
        from torchmetrics_tpu.serve.snapshot import read_host

        cms = read_host(self, ("cms",))["cms"]
        return float((cms > 0).mean())


def merge_topk(cms: Array, candidate_ids: Array, k: int, depth: int, width: int) -> Tuple[Array, Array]:
    """Top-k over a candidate id set, counts re-estimated from ``cms``.

    Fixed-shape and jittable: duplicates collapse by sorting and masking the
    non-first element of every equal run (all copies of one id carry the SAME
    grid estimate, so keeping the first is exact); ``-1`` empties rank last.
    Shared by :class:`HeavyHitters.update`, the spill path in
    ``serve/tenancy.py``, and the ``hh-ids`` packed fold.
    """
    est = cms_query(cms, canon_u32(candidate_ids), depth, width)
    neg_one = jnp.asarray(-1, cms.dtype)
    est = jnp.where(candidate_ids < 0, neg_one, est.astype(cms.dtype))
    order = jnp.argsort(candidate_ids)
    sid = candidate_ids[order]
    sest = est[order]
    dup = jnp.concatenate([jnp.zeros((1,), bool), sid[1:] == sid[:-1]])
    sest = jnp.where(dup, neg_one, sest)
    top_est, top_pos = jax.lax.top_k(sest, k)
    ids = jnp.where(top_est >= 0, sid[top_pos], jnp.asarray(-1, sid.dtype))
    counts = jnp.maximum(top_est, 0)
    return ids, counts
