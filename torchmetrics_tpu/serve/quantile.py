"""Mergeable KLL-style quantile sketch as a first-class Metric state.

Latency/SLO percentiles are the one serving answer the existing surfaces only
approximate per-pod (``diag/hist.py``'s geometric buckets carry a ≤ 18.92%
one-sided *value* error); composing them across a fleet needs a sketch whose
merge is exact in its *rank* guarantee. :class:`KLLSketch` is that state:

- **Fixed-capacity compactor levels as one flat device array.** State is a
  ``(levels, k + 1)`` float32 array: row ``i`` holds up to ``k`` items of
  implicit weight ``2**i`` (``+inf`` pads the free slots; the trailing column
  is the row's live-item count). Memory never grows with the stream.
- **In-graph update through the engine.** ``update()`` chunks the batch into
  ``<= k`` sorted runs and pushes each through the compaction cascade — pure
  ``jnp`` ops with static shapes, so the whole body lowers into the compiled
  update dispatch like any accumulator state.
- **Deterministic compaction.** A full level sorts its ``2**i``-weight items
  and promotes the odd-indexed half to level ``i + 1`` (weight doubles); an
  odd leftover item stays put, so total weight is conserved exactly —
  ``sum(count_i * 2**i) == n`` always. No randomness: replays and re-merges
  are byte-stable.
- **Mergeable.** :func:`kll_merge` folds stacked sketches pairwise through
  the same cascade. It is the sketch's ``dist_reduce_fx``, so the packed
  epoch sync folds it cross-rank via the ``custom`` role and
  ``Metric.merge_state`` / the federation aggregator fold it cross-pod —
  left-folded in canonical member order, hence byte-stable for a fixed
  membership regardless of arrival order.

**Proven rank-error bound** (deterministic-compaction analysis): one
compaction at level ``i`` displaces any fixed rank by at most ``2**i``
(between two consecutive promoted items exactly one discarded item's weight
moves past the query point); each such compaction consumes at least
``(k - 1) * 2**i / 2`` weight from below, so at most ``~2n / (k * 2**i)``
occur; summing the per-level products over the ``ceil(log2(n / k)) + 1``
active levels gives

    ``|rank(estimate) - ceil(q * n)| <= 2 * n * (ceil(log2(n / k)) + 1) / k``

— :meth:`KLLSketch.rank_error_bound` returns exactly this, and the bench
``federation`` scenario verifies p50/p99 against exact quantiles at 10⁶
samples. At the default ``k = 256`` that is ~5% of ``n`` at 10⁶ samples;
``k = 2048`` tightens it under 1%.

The sketch is *seeded from the* ``diag/hist.py`` *geometric-bucket scheme*: a
rider state bins every sample over the shared :data:`~torchmetrics_tpu.diag.
hist.BOUNDS` (sum-merged, so it composes exactly), and
:meth:`KLLSketch.coarse_quantile` answers with that scheme's proven ≤ 18.92%
one-sided value error — the cheap cross-check for the KLL estimate.
"""

from __future__ import annotations

from math import ceil, log2
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.diag.hist import BOUNDS, GROWTH
from torchmetrics_tpu.metric import Metric

Array = jax.Array

__all__ = ["KLLSketch", "kll_merge"]

_N_BOUNDS = len(BOUNDS)


def _merge2(a: Array, b: Array) -> Array:
    """Merge two ``(L, k + 1)`` compactor states through the cascade.

    Per level: concatenate both rows plus the carry from below (a sorted
    ``4k`` window — ``+inf`` padding keeps every shape static), keep the
    combined run when it fits in ``k`` slots, otherwise promote the
    odd-indexed half of the even prefix (weight doubles into the carry) and
    retain the odd leftover item. Weight is conserved exactly at every level.
    """
    L, k1 = a.shape
    k = k1 - 1
    dtype = a.dtype
    carry_items = jnp.full((2 * k,), jnp.inf, dtype)
    carry_cnt = jnp.zeros((), dtype)
    rows = []
    for i in range(L):
        combined = jnp.sort(jnp.concatenate([a[i, :k], b[i, :k], carry_items]))
        total = a[i, k] + b[i, k] + carry_cnt
        fits = total <= k
        m2 = jnp.floor(total * 0.5) * 2.0  # even prefix length
        leftover = total - m2  # 0.0 or 1.0
        odd = combined[1::2]  # candidates for promotion (odd global indices)
        odd_pos = jnp.arange(odd.shape[0], dtype=dtype) * 2.0 + 1.0
        promoted = jnp.where(odd_pos < m2, odd, jnp.inf)
        leftover_item = combined[jnp.clip(m2, 0, combined.shape[0] - 1).astype(jnp.int32)]
        compact_row = jnp.full((k,), jnp.inf, dtype).at[0].set(
            jnp.where(leftover > 0, leftover_item, jnp.inf)
        )
        new_items = jnp.where(fits, combined[:k], compact_row)
        new_cnt = jnp.where(fits, total, leftover)
        rows.append(jnp.concatenate([new_items, new_cnt[None]]))
        carry_items = jnp.where(fits, jnp.full((2 * k,), jnp.inf, dtype), promoted)
        carry_cnt = jnp.where(fits, jnp.zeros((), dtype), m2 * 0.5)
    # levels are sized so k * 2**(levels-1) exceeds any realistic stream; a
    # carry escaping the top would be the only weight-losing path (documented
    # capacity bound, validated at construction)
    return jnp.stack(rows)


def kll_merge(stacked: Array) -> Array:
    """Fold stacked ``(M, L, k + 1)`` sketches — the ``dist_reduce_fx``.

    Left-fold in stack order: deterministic, so a fixed member ordering gives
    a byte-stable merged sketch; the rank-error bound composes additively
    over members (each input's compaction history is preserved, the merge
    adds at most one cascade per level pair).
    """
    out = stacked[0]
    for i in range(1, stacked.shape[0]):
        out = _merge2(out, stacked[i])
    return out


def _scan_full_runs(state: Array, runs: Array, levels: int, k: int) -> Array:
    """Fold ``(m, k)`` sorted full runs into ``state`` — one ``lax.scan``.

    The cascade per run is identical to :func:`_merge2` over a wrapped
    single-level state (same merge order, byte-identical result); the scan
    form exists so an ``m``-run batch costs ONE dispatch instead of ``m``
    eager cascades.
    """
    cnt = jnp.asarray(float(k), runs.dtype)

    def body(st: Array, run: Array):
        return _merge2(st, _wrap_run(run, cnt, levels, k)), None

    out, _ = jax.lax.scan(body, state, runs)
    return out


_scan_full_runs = jax.jit(_scan_full_runs, static_argnums=(2, 3))


def _wrap_run(run: Array, cnt: Array, levels: int, k: int) -> Array:
    """Lift one sorted ``<= k`` run into a single-level compactor state."""
    dtype = run.dtype
    row0 = jnp.concatenate([run, cnt[None]])
    rest = jnp.concatenate(
        [jnp.full((levels - 1, k), jnp.inf, dtype), jnp.zeros((levels - 1, 1), dtype)],
        axis=1,
    )
    return jnp.concatenate([row0[None], rest], axis=0)


def _sketch_quantile(state: Array, q: float) -> Array:
    """Weighted-rank quantile over the flattened (item, 2**level) pairs.

    Rank convention matches ``diag/hist.py`` (``sorted(x)[ceil(q * n) - 1]``,
    the "higher" interpolation): the smallest retained item whose cumulative
    weight reaches ``ceil(q * W)``.
    """
    L, k1 = state.shape
    k = k1 - 1
    items = state[:, :k].reshape(-1)
    level_w = jnp.repeat(2.0 ** jnp.arange(L, dtype=state.dtype), k)
    weights = jnp.where(jnp.isfinite(items), level_w, 0.0)
    order = jnp.argsort(items)
    sorted_items = items[order]
    cum_w = jnp.cumsum(weights[order])
    total = cum_w[-1]
    rank = jnp.clip(jnp.ceil(q * total), 1.0, jnp.maximum(total, 1.0))
    pos = jnp.searchsorted(cum_w, rank)
    return sorted_items[jnp.clip(pos, 0, sorted_items.shape[0] - 1)]


class KLLSketch(Metric):
    """Mergeable quantile sketch: KLL compactor levels as one device state.

    Args:
        k: per-level compactor capacity (even int >= 8; larger = tighter
            rank-error bound, ``2 * n * (ceil(log2(n/k)) + 1) / k``).
        levels: compactor levels; capacity is ``k * 2**(levels - 1)`` total
            weight (the default 20 levels hold > 10⁸ samples at ``k = 256``).
        qs: the quantiles ``compute()`` returns (a fixed tuple, so the
            compute graph is static).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.serve import KLLSketch
        >>> sketch = KLLSketch(k=64)
        >>> sketch.update(jnp.arange(1000.0))
        >>> p50, p99 = sketch.compute()
        >>> bool(abs(float(p50) - 500.0) < 150)
        True
    """

    full_state_update = True
    higher_is_better = None
    is_differentiable = False

    def __init__(
        self,
        k: int = 256,
        levels: int = 20,
        qs: Sequence[float] = (0.5, 0.99),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(k, int) and k >= 8 and k % 2 == 0):
            raise ValueError(f"Expected argument `k` to be an even int >= 8 but got {k}")
        if not (isinstance(levels, int) and 4 <= levels <= 32):
            raise ValueError(f"Expected argument `levels` to be an int in [4, 32] but got {levels}")
        self.k = k
        self.levels = levels
        self.qs = tuple(float(q) for q in qs)
        if not all(0.0 < q <= 1.0 for q in self.qs):
            raise ValueError(f"Expected argument `qs` to hold floats in (0, 1] but got {qs}")
        default = jnp.concatenate(
            [jnp.full((levels, k), jnp.inf, jnp.float32), jnp.zeros((levels, 1), jnp.float32)],
            axis=1,
        )
        # the joint (items, counts) layout is ONE state so the callable
        # dist_reduce_fx merges it atomically through every fold path: the
        # packed plan's `custom` role, Metric.merge_state's callable branch,
        # and the federation aggregator's cross-pod fold
        self.add_state("compactors", default=default, dist_reduce_fx=kll_merge)
        # geometric-bucket rider seeded from diag/hist.py: sum-merged counts
        # over the shared quarter-octave BOUNDS — the ≤ 18.92% one-sided
        # value-error cross-check (and the scheme this sketch grew out of)
        self.add_state(
            "geo_counts", default=jnp.zeros((_N_BOUNDS + 1,), jnp.float32), dist_reduce_fx="sum"
        )
        self._geo_bounds = jnp.asarray(BOUNDS, dtype=jnp.float32)
        from torchmetrics_tpu.serve import stats as _serve_stats

        _serve_stats.register_sketch(self)

    # ------------------------------------------------------------------ update

    def update(self, values: Any) -> None:
        """Fold a batch of finite samples into the sketch (in-graph cascade)."""
        v = jnp.ravel(jnp.asarray(values)).astype(jnp.float32)
        state = self.compactors
        n = int(v.shape[0])
        full = n // self.k
        if full:
            runs = jnp.sort(v[: full * self.k].reshape(full, self.k), axis=1)
            state = _scan_full_runs(state, runs, self.levels, self.k)
        if n - full * self.k or not n:
            chunk = v[full * self.k :]
            cnt = jnp.asarray(float(chunk.shape[0]), jnp.float32)
            run = jnp.sort(jnp.pad(chunk, (0, self.k - chunk.shape[0]), constant_values=jnp.inf))
            state = _merge2(state, _wrap_run(run, cnt, self.levels, self.k))
        self.compactors = state
        if n:
            idx = jnp.searchsorted(self._geo_bounds, v)
            self.geo_counts = self.geo_counts.at[idx].add(1.0)

    # ------------------------------------------------------------------ compute

    def compute(self) -> Array:
        """The configured quantiles, in ``qs`` order, as one array."""
        return jnp.stack([_sketch_quantile(self.compactors, q) for q in self.qs])

    def quantile(self, q: float) -> Array:
        """Point query: the ``q``-quantile estimate from the compactor levels."""
        return _sketch_quantile(self.compactors, float(q))

    def coarse_quantile(self, q: float) -> Array:
        """The geometric-bucket estimate (``diag/hist.py`` semantics).

        Upper bound of the bucket holding the rank — within ``[exact,
        exact * GROWTH]`` (≤ 18.92% one-sided) for in-range positive samples;
        overflow-bucket ranks return the top boundary (the scheme's honest
        ceiling — unlike :class:`~torchmetrics_tpu.diag.hist.Histogram` this
        state keeps no exact max).
        """
        counts = self.geo_counts
        cum = jnp.cumsum(counts)
        total = cum[-1]
        rank = jnp.clip(jnp.ceil(q * total), 1.0, jnp.maximum(total, 1.0))
        pos = jnp.searchsorted(cum, rank)
        return self._geo_bounds[jnp.clip(pos, 0, _N_BOUNDS - 1)]

    # ------------------------------------------------------------------ bounds

    def rank_error_bound(self, n: int) -> int:
        """The proven worst-case rank displacement after ``n`` samples.

        ``2 * n * (ceil(log2(n / k)) + 1) / k`` — see the module docstring
        for the derivation; merging sketches whose sample counts sum to ``n``
        stays within the same bound (compaction histories compose, they do
        not multiply).
        """
        n = int(n)
        if n <= self.k:
            return 0  # nothing has ever compacted: the sketch is exact
        return ceil(2.0 * n * (ceil(log2(n / self.k)) + 1) / self.k)

    def growth_bound(self) -> float:
        """The coarse (geometric-bucket) one-sided relative value-error bound."""
        return GROWTH - 1.0

    # ------------------------------------------------------------------ views

    def fill_ratio(self) -> float:
        """Fraction of occupied compactor slots — the scrape saturation gauge."""
        from torchmetrics_tpu.serve.snapshot import read_host

        state = read_host(self, ("compactors",))["compactors"]
        return float(np.isfinite(state[:, : self.k]).mean())

    def total_weight(self) -> int:
        """Exact samples represented (weight is conserved by construction)."""
        from torchmetrics_tpu.serve.snapshot import read_host

        state = read_host(self, ("compactors",))["compactors"]
        return int(round(float((state[:, self.k] * (2.0 ** np.arange(self.levels))).sum())))
