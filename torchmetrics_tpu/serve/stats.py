"""Serving-layer counters, object registries, and env knobs.

Import-light on purpose (no Metric / engine imports): ``diag/telemetry.py``
pulls :func:`serve_state` into every scrape, and the serve objects register
themselves here at construction — a :class:`weakref.WeakValueDictionary`
keyed by ``id(obj)`` (NEVER a WeakSet: ``Metric.__hash__`` covers live state
array ids and changes every update).

Env contract (PR-7/PR-8 rule): unrecognized values FAIL LOUD with
:class:`~torchmetrics_tpu.utilities.exceptions.TorchMetricsUserError` instead
of silently disabling the knob.

- ``TORCHMETRICS_TPU_SERVE_CAPACITY`` — default tenant-slot capacity for
  :class:`~torchmetrics_tpu.serve.tenancy.TenantSlices` (power-of-two int).
- ``TORCHMETRICS_TPU_SERVE_PORT`` — default bind port for
  :class:`~torchmetrics_tpu.serve.sidecar.MetricsSidecar` (0 = ephemeral).
- ``TORCHMETRICS_TPU_SERVE_SNAPSHOT_RETRIES`` — consistency-retry budget for
  :func:`~torchmetrics_tpu.serve.snapshot.take_snapshot`.
- ``TORCHMETRICS_TPU_FEDERATION_RETRIES`` — bounded-pull retry budget for
  :class:`~torchmetrics_tpu.serve.federation.FederationAggregator`.
- ``TORCHMETRICS_TPU_FLEET_PULL_MS`` — per-pull deadline (ms) for
  :class:`~torchmetrics_tpu.serve.fleet.FleetTelemetry` telemetry rounds
  (unset/0 = no deadline).
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Any, Dict

from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "federation_retries",
    "fleet_pull_ms",
    "note_scrape",
    "note_snapshot",
    "register_federation",
    "register_fleet",
    "register_sketch",
    "register_tenancy",
    "reset_serve_stats",
    "serve_state",
]

_LOCK = threading.Lock()

#: process-wide monotonic counters (scrapes come from the sidecar thread, so
#: every bump takes the lock; the hot update loop never touches these)
_COUNTERS: Dict[str, float] = {  # guarded-by: _LOCK
    "scrapes": 0,
    "scrape_seconds": 0.0,
    "snapshots": 0,
    "snapshot_retries": 0,
}

#: registries keyed by a process-stable registration sequence number — the
#: number becomes part of the Prometheus owner label, so two live instances of
#: the same class can never emit duplicate label sets (which would fail the
#: whole scrape at the Prometheus parser)
_SEQ = iter(range(1, 1 << 62)).__next__
_TENANCIES: "weakref.WeakValueDictionary[int, Any]" = weakref.WeakValueDictionary()
_SKETCHES: "weakref.WeakValueDictionary[int, Any]" = weakref.WeakValueDictionary()
_FEDERATIONS: "weakref.WeakValueDictionary[int, Any]" = weakref.WeakValueDictionary()
_FLEETS: "weakref.WeakValueDictionary[int, Any]" = weakref.WeakValueDictionary()


def register_tenancy(obj: Any) -> None:
    _TENANCIES[_SEQ()] = obj


def register_sketch(obj: Any) -> None:
    _SKETCHES[_SEQ()] = obj


def register_federation(obj: Any) -> None:
    _FEDERATIONS[_SEQ()] = obj


def register_fleet(obj: Any) -> None:
    _FLEETS[_SEQ()] = obj


def note_scrape(seconds: float) -> None:
    with _LOCK:
        _COUNTERS["scrapes"] += 1
        _COUNTERS["scrape_seconds"] += float(seconds)


def note_snapshot(retries: int) -> None:
    with _LOCK:
        _COUNTERS["snapshots"] += 1
        _COUNTERS["snapshot_retries"] += int(retries)


def reset_serve_stats() -> None:
    """Zero the counters (registries are weak — they empty themselves)."""
    with _LOCK:
        _COUNTERS.update(scrapes=0, scrape_seconds=0.0, snapshots=0, snapshot_retries=0)


def serve_state() -> Dict[str, Any]:
    """One JSON-serializable dict for telemetry: counters + live-object gauges.

    Gauge reads (tenant counts, sketch fill ratios) are host transfers by
    design and ride each object's own sanctioned boundary — this is the
    scrape path, not the hot loop.
    """
    with _LOCK:
        out: Dict[str, Any] = dict(_COUNTERS)

    def _note_failed(owner: str, exc: Exception) -> None:
        # a half-built / mid-donation object must not kill a scrape, but the
        # skip must not be silent either — it lands in the flight recorder
        from torchmetrics_tpu.diag import trace as _diag

        _diag.record("serve.scrape.error", owner, error=f"{type(exc).__name__}: {exc}")

    tenants = []
    for seq, obj in sorted(_TENANCIES.items()):
        owner = f"{type(obj).__name__}#{seq}"
        try:
            tenants.append({
                "owner": owner,
                "tenants": obj.tenant_count(),
                "spilled": obj.spilled_count(),
            })
        except Exception as exc:  # noqa: BLE001
            _note_failed(owner, exc)
    sketches = []
    for seq, obj in sorted(_SKETCHES.items()):
        owner = f"{type(obj).__name__}#{seq}"
        try:
            sketches.append({"owner": owner, "fill_ratio": obj.fill_ratio()})
        except Exception as exc:  # noqa: BLE001
            _note_failed(owner, exc)
    out["tenancies"] = sorted(tenants, key=lambda t: t["owner"])
    out["sketches"] = sorted(sketches, key=lambda s: s["owner"])
    federations = []
    for seq, obj in sorted(_FEDERATIONS.items()):
        owner = f"{type(obj).__name__}#{seq}"
        try:
            federations.append({"owner": owner, **obj.federation_state()})
        except Exception as exc:  # noqa: BLE001
            _note_failed(owner, exc)
    out["federations"] = sorted(federations, key=lambda f: f["owner"])
    fleets = []
    for seq, obj in sorted(_FLEETS.items()):
        owner = f"{type(obj).__name__}#{seq}"
        try:
            fleets.append({"owner": owner, **obj.fleet_state()})
        except Exception as exc:  # noqa: BLE001
            _note_failed(owner, exc)
    out["fleets"] = sorted(fleets, key=lambda f: f["owner"])
    return out


def _env_int(name: str, default: int, lo: int, hi: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        value = int(raw.strip())
    except ValueError:
        value = None
    if value is None or not (lo <= value <= hi):
        raise TorchMetricsUserError(
            f"Invalid {name}={raw!r}: expected an integer in [{lo}, {hi}]."
            " Unset the variable to use the default."
        )
    return value


def default_capacity() -> int:
    cap = _env_int("TORCHMETRICS_TPU_SERVE_CAPACITY", 4096, 2, 1 << 24)
    if cap & (cap - 1):
        raise TorchMetricsUserError(
            f"Invalid TORCHMETRICS_TPU_SERVE_CAPACITY={cap}: must be a power of two"
            " (the tenant table probes with power-of-two masking)."
        )
    return cap


def default_port() -> int:
    return _env_int("TORCHMETRICS_TPU_SERVE_PORT", 0, 0, 65535)


def snapshot_retries() -> int:
    return _env_int("TORCHMETRICS_TPU_SERVE_SNAPSHOT_RETRIES", 8, 1, 1000)


def federation_retries() -> int:
    return _env_int("TORCHMETRICS_TPU_FEDERATION_RETRIES", 2, 0, 100)


def fleet_pull_ms() -> "float | None":
    """Per-pull deadline (ms) for fleet telemetry rounds; None = no deadline."""
    value = _env_int("TORCHMETRICS_TPU_FLEET_PULL_MS", 0, 0, 86_400_000)
    return float(value) if value else None
