"""MetricCollection with compute groups.

Capability parity: reference ``src/torchmetrics/collections.py`` (618 LoC):
``update:182``, ``_merge_compute_groups:209``, ``_equal_metric_states:244``,
``_compute_groups_create_state_ref:269``, ``_compute_and_reduce:292``,
``add_metrics:356``, group-aware ``keys/items/values:467-494``.

TPU-first twist: states are immutable ``jax.Array``s, so "sharing by reference" is a
cheap copy of array references from the group leader into members — no aliasing
hazards, and ``copy_state`` semantics (reference breaks aliasing via deepcopy) are
automatic because members can never mutate the leader's arrays.
"""

from __future__ import annotations

from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, Hashable, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import allclose
from torchmetrics_tpu.utilities.prints import rank_zero_warn


class MetricCollection:
    """Dict of metrics sharing one call pattern, with automatic compute groups (reference ``collections.py:34``).

    Metrics with identical states (e.g. accuracy/precision/recall over the same
    stat-scores) form a compute group: only the group leader runs ``update``; members
    receive the leader's state (array references) lazily.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MetricCollection
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassPrecision
        >>> target = jnp.asarray([0, 2, 0, 2, 0, 1, 0, 2])
        >>> preds = jnp.asarray([2, 1, 2, 0, 1, 2, 2, 2])
        >>> metrics = MetricCollection([MulticlassAccuracy(num_classes=3, average='micro'), MulticlassPrecision(num_classes=3, average='macro')])
        >>> result = metrics(preds, target)
        >>> print({k: round(float(v), 4) for k, v in sorted(result.items())})
        {'MulticlassAccuracy': 0.125, 'MulticlassPrecision': 0.0667}
    """

    _groups: Dict[int, List[str]]

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
    ) -> None:
        self._modules: "OrderedDict[str, Metric]" = OrderedDict()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        self._groups_checked: bool = False
        self._state_is_copy: bool = False

        self.add_metrics(metrics, *additional_metrics)

    # ------------------------------------------------------------------ update paths

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Per-metric ``forward`` (batch values); kwargs filtered per signature (reference ``:153-160``).
    """
        return self._compute_and_reduce("forward", *args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update each compute group's leader only (reference ``collections.py:182-207``)."""
        if self._groups_checked:
            for cg in self._groups.values():
                m0 = self._modules[cg[0]]
                m0.update(*args, **m0._filter_kwargs(**kwargs))
            if self._state_is_copy:
                self._compute_groups_create_state_ref()
                self._state_is_copy = False
        else:
            for m in self.values(copy_state=False):
                m.update(*args, **m._filter_kwargs(**kwargs))
            if self._enable_compute_groups:
                self._merge_compute_groups()
                self._compute_groups_create_state_ref()
                self._groups_checked = True

    def _merge_compute_groups(self) -> None:
        """One-pass signature-bucketed group merge (behavior parity with reference
        ``collections.py:209-242``, algorithm owned here).

        Each group is fingerprinted by its leader's state STRUCTURE
        (``_state_signature``: sorted state names, container kinds, shapes, dtypes) —
        pure metadata, no device work. Only groups with identical fingerprints can
        possibly share state, so value comparison (``_states_allclose``, the only part
        that touches arrays) runs within a bucket: each group folds into the first
        bucket representative whose state values match, else becomes a new
        representative. Single pass, no deepcopy, no fixed-point rescan — the
        signature bucketing makes transitive merging fall out of representative
        chaining instead of repeated O(n²) sweeps.
        """
        merged: List[List[str]] = []
        buckets: Dict[tuple, List[List[str]]] = {}
        for members in self._groups.values():
            leader = self._modules[members[0]]
            sig = self._state_signature(leader)
            if sig is None:  # stateless metrics never share a group
                merged.append(members)
                continue
            for rep_members in buckets.setdefault(sig, []):
                if self._states_allclose(self._modules[rep_members[0]], leader):
                    rep_members.extend(members)
                    break
            else:
                buckets[sig].append(members)
                merged.append(members)
        self._groups = dict(enumerate(merged))

    @staticmethod
    def _state_signature(metric: Metric) -> Optional[tuple]:
        """Structural fingerprint of a metric's registered states, or None if stateless.

        Two metrics can only share a compute group when their fingerprints are equal;
        comparing fingerprints costs no device traffic.
        """
        if not metric._defaults:
            return None
        sig = []
        for key in sorted(metric._defaults):
            val = getattr(metric, key)
            if isinstance(val, list):
                sig.append((key, "list", tuple((tuple(v.shape), str(v.dtype)) for v in val)))
            else:
                sig.append((key, "array", tuple(val.shape), str(val.dtype)))
        return tuple(sig)

    @staticmethod
    def _states_allclose(metric1: Metric, metric2: Metric) -> bool:
        """Value equality of two structurally identical metrics' states."""
        for key in metric1._defaults:
            state1 = getattr(metric1, key)
            state2 = getattr(metric2, key)
            if isinstance(state1, list):
                if not all(allclose(s1, s2) for s1, s2 in zip(state1, state2)):
                    return False
            elif not allclose(state1, state2):
                return False
        return True

    def _compute_groups_create_state_ref(self, copy: bool = False) -> None:
        """Propagate leader state (array refs) to group members (reference ``collections.py:269-286``).

        Arrays are immutable so ``copy`` only matters for list states (shallow-copied).
        """
        if not self._state_is_copy:
            for cg in self._groups.values():
                m0 = self._modules[cg[0]]
                for i in range(1, len(cg)):
                    mi = self._modules[cg[i]]
                    for state in m0._defaults:
                        m0_state = getattr(m0, state)
                        setattr(mi, state, list(m0_state) if copy and isinstance(m0_state, list) else m0_state)
                    mi._update_count = m0._update_count
                    mi._computed = None
                    # fold markers travel with the states they describe, else a member
                    # holding the leader's stacked None-reduced state would re-wrap it
                    mi._none_folded = set(m0._none_folded)
        self._state_is_copy = copy

    # ------------------------------------------------------------------ compute

    def compute(self) -> Dict[str, Any]:
        """Per-metric compute into one flat dict (reference ``collections.py:288-291``)."""
        return self._compute_and_reduce("compute")

    def _compute_and_reduce(self, method_name: str, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Reference ``collections.py:292-326``."""
        result = {}
        for k, m in self.items(keep_base=True, copy_state=False):
            if method_name == "compute":
                res = m.compute()
            elif method_name == "forward":
                res = m(*args, **m._filter_kwargs(**kwargs))
            else:
                raise ValueError(f"method_name should be either 'compute' or 'forward', but got {method_name}")
            if isinstance(res, dict):
                for key, v in res.items():
                    if getattr(m, "prefix", None) is not None:
                        key = f"{m.prefix}{key}"
                    if getattr(m, "postfix", None) is not None:
                        key = f"{key}{m.postfix}"
                    result[key] = v
            else:
                result[k] = res
        return {self._set_name(k): v for k, v in result.items()}

    # ------------------------------------------------------------------ lifecycle

    def reset(self) -> None:
        """Reset every metric (reference ``collections.py:328-334``)."""
        for m in self.values(copy_state=False):
            m.reset()
        if self._enable_compute_groups and self._groups_checked:
            self._compute_groups_create_state_ref()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        """Deep copy, optionally re-prefixed (reference ``collections.py:336-349``)."""
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        """Toggle state persistence for all metrics (reference ``collections.py:351-354``)."""
        for m in self.values(copy_state=False):
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        """Flat state dict keyed by metric name."""
        destination: Dict[str, Any] = {}
        for k, m in self.items(keep_base=True, copy_state=False):
            m.state_dict(destination, prefix=f"{k}.")
        return destination

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        """Restore from ``state_dict``."""
        for k, m in self.items(keep_base=True, copy_state=False):
            m.load_state_dict(state_dict, prefix=f"{k}.")

    # ------------------------------------------------------------------ membership

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Register metrics from dict/sequence/instance (reference ``collections.py:356-420``)."""
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            metrics = list(metrics)
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                rank_zero_warn(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of"
                        " `torchmetrics_tpu.Metric` or `torchmetrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        v.postfix = metric.postfix
                        v.prefix = metric.prefix
                        self._modules[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of"
                        " `torchmetrics_tpu.Metric` or `torchmetrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = metric.__class__.__name__
                    if name in self._modules:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        v.postfix = metric.postfix
                        v.prefix = metric.prefix
                        self._modules[k] = v
        else:
            raise ValueError(
                "Unknown input to MetricCollection. Expected, `Metric`, `MetricCollection` or `dict`/`sequence` of the"
                f" previous, but got {metrics}"
            )

        self._groups_checked = False
        if self._enable_compute_groups:
            self._init_compute_groups()
        else:
            self._groups = {}

    def _init_compute_groups(self) -> None:
        """User-specified or singleton groups (reference ``collections.py:422-441``)."""
        if isinstance(self._enable_compute_groups, list):
            self._groups = dict(enumerate(self._enable_compute_groups))
            for v in self._groups.values():
                for metric in v:
                    if metric not in self._modules:
                        raise ValueError(
                            f"Input {metric} in `compute_groups` argument does not match a metric in the"
                            f" collection. Please make sure that {self._enable_compute_groups} matches"
                            f" {list(self._modules.keys())}"
                        )
            self._groups_checked = True
        else:
            self._groups = {i: [str(k)] for i, k in enumerate(self._modules.keys())}

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        """Current compute groups (reference ``collections.py:443-446``)."""
        return self._groups

    # ------------------------------------------------------------------ dict protocol

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def _to_renamed_ordered_dict(self) -> OrderedDict:
        od = OrderedDict()
        for k, v in self._modules.items():
            od[self._set_name(k)] = v
        return od

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def __len__(self) -> int:
        return len(self._modules)

    def __contains__(self, key: str) -> bool:
        return key in self._modules

    def keys(self, keep_base: bool = False) -> Iterable[Hashable]:
        """Metric names (reference ``collections.py:467-475``)."""
        if keep_base:
            return self._modules.keys()
        return self._to_renamed_ordered_dict().keys()

    def items(self, keep_base: bool = False, copy_state: bool = True) -> Iterable[Tuple[str, Metric]]:
        """(name, metric) pairs; propagates group state first (reference ``collections.py:477-488``)."""
        self._compute_groups_create_state_ref(copy_state)
        if keep_base:
            return self._modules.items()
        return self._to_renamed_ordered_dict().items()

    def values(self, copy_state: bool = True) -> Iterable[Metric]:
        """Metrics; propagates group state first (reference ``collections.py:490-498``)."""
        self._compute_groups_create_state_ref(copy_state)
        return self._modules.values()

    def __getitem__(self, key: str, copy_state: bool = True) -> Metric:
        """Metric by (renamed) key (reference ``collections.py:500-514``)."""
        self._compute_groups_create_state_ref(copy_state)
        if self.prefix or self.postfix:
            key = key.removeprefix(self.prefix or "").removesuffix(self.postfix or "")
        return self._modules[key]

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for k, v in self._modules.items():
            repr_str += f"\n  {k}: {v!r}"
        if self.prefix:
            repr_str += f",\n  prefix={self.prefix}"
        if self.postfix:
            repr_str += f",\n  postfix={self.postfix}"
        return repr_str + "\n)"

    def set_dtype(self, dst_type: Any) -> "MetricCollection":
        """Cast all metric states (reference ``collections.py`` dtype transfer)."""
        for m in self.values(copy_state=False):
            m.set_dtype(dst_type)
        return self

    def to(self, device: Any) -> "MetricCollection":
        """Move all metric states to ``device``."""
        for m in self.values(copy_state=False):
            m.to(device)
        return self

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None, together: bool = False) -> Any:
        """Plot all metrics (reference ``collections.py`` plot)."""
        import matplotlib.pyplot as plt

        if val is None:
            val = self.compute()
        if together:
            from torchmetrics_tpu.utilities.plot import plot_single_or_multi_val

            return plot_single_or_multi_val(val, ax=ax)
        fig_axs = []
        for k, m in self.items(keep_base=False, copy_state=False):
            f, a = m.plot(val[k] if isinstance(val, dict) and k in val else None)
            fig_axs.append((f, a))
        del plt
        return fig_axs
