"""MetricCollection — canonical-state compute groups and fused dispatch.

Capability parity with the reference's ``MetricCollection`` (dict-of-metrics with
one call pattern, automatic compute groups, prefix/postfix renaming, group-aware
views), architected TPU-first instead of porting the reference's
attribute-aliasing design:

- **Canonical state + views.** Each :class:`_ComputeGroup` designates one member
  as the canonical owner of the group's state; the remaining members are VIEWS
  that receive the owner's array references only when someone looks at them
  (``items``/``values``/``compute``). States are immutable ``jax.Array``s, so a
  view can never corrupt the canonical copy and "breaking aliasing" (the
  reference's deepcopy dance) reduces to shallow-copying list states on demand.
- **Fused dispatch.** With the fused update engine enabled (``engine/``), one
  collection step compiles every group owner's update body into a SINGLE XLA
  executable with donated state buffers (``engine/fusion.py``) — an N-metric
  step costs one dispatch instead of N, which is the difference that matters at
  pod scale where the dispatch floor dominates the collective cost.
- **Structure-first group discovery.** Groups merge by comparing a cheap
  structural fingerprint (state names/kinds/shapes/dtypes) before any device
  values are touched; only fingerprint-equal candidates pay the value
  comparison. Single pass, no deepcopy, no fixed-point rescan.
"""

from __future__ import annotations

from collections import OrderedDict
from copy import deepcopy
from time import perf_counter as _perf_counter
from typing import Any, Dict, Hashable, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import profile as _profile
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import allclose
from torchmetrics_tpu.utilities.prints import rank_zero_warn


class _ComputeGroup:
    """A set of metric names whose states are provably identical.

    The FIRST name is the canonical owner: it is the only member whose
    ``update`` runs, and its state arrays are the group's single source of
    truth. Everyone else is a view to be materialized from the owner.
    """

    __slots__ = ("names",)

    def __init__(self, names: Sequence[str]) -> None:
        self.names: List[str] = list(names)

    @property
    def owner(self) -> str:
        return self.names[0]

    def absorb(self, other: "_ComputeGroup") -> None:
        self.names.extend(other.names)

    def materialize_views(self, modules: Dict[str, Metric], copy: bool = False) -> None:
        """Push the owner's state references into every view member.

        Arrays are immutable so reference sharing is always safe; ``copy`` only
        matters for list states, which are shallow-copied so a view appending
        host-side cannot grow the canonical list.
        """
        import weakref

        owner = modules[self.owner]
        owner_ref = weakref.ref(owner)
        for name in self.names[1:]:
            view = modules[name]
            for state in owner._defaults:
                value = getattr(owner, state)
                setattr(view, state, list(value) if copy and isinstance(value, list) else value)
            view._update_count = owner._update_count
            view._computed = None
            # fold markers travel with the states they describe, else a view
            # holding the owner's stacked None-reduced state would re-wrap it
            view._none_folded = set(owner._none_folded)
            # a view OBSERVES the owner's state: its drain hooks must flush
            # the OWNER's scan queue (engine/scan.py staleness contract) —
            # the view itself never enqueues, so flush_metric(view) alone
            # would match nothing and read up to K-1 steps stale
            view._scan_peer = owner_ref


def _state_fingerprint(metric: Metric) -> Optional[tuple]:
    """Structural digest of a metric's registered states; None if stateless.

    Two metrics can only share a group when their fingerprints match, and
    comparing fingerprints costs no device traffic — value equality (the only
    part that reads arrays) runs strictly within a fingerprint bucket.
    """
    if not metric._defaults:
        return None
    sig = []
    for key in sorted(metric._defaults):
        val = getattr(metric, key)
        if isinstance(val, list):
            sig.append((key, "list", tuple((tuple(v.shape), str(v.dtype)) for v in val)))
        else:
            sig.append((key, "array", tuple(val.shape), str(val.dtype)))
    return tuple(sig)


def _states_equal(metric1: Metric, metric2: Metric) -> bool:
    """Value equality of two structurally identical metrics' states.

    Runs ONCE per collection, on the first step (group discovery). The value
    comparison necessarily reads device state back to the host, so it is a
    sanctioned boundary for the diag transfer guard — a strict-guarded hot
    loop must not flag the one-time discovery as a hot-loop readback.
    """
    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    with transfer_allowed("group-discovery"):
        for key in metric1._defaults:
            state1 = getattr(metric1, key)
            state2 = getattr(metric2, key)
            if isinstance(state1, list):
                if not all(allclose(s1, s2) for s1, s2 in zip(state1, state2)):
                    return False
            elif not allclose(state1, state2):
                return False
    return True


class MetricCollection:
    """Dict of metrics sharing one call pattern, with automatic compute groups.

    Metrics whose states are provably identical (e.g. accuracy/precision/recall
    over the same stat-scores) form a compute group: only the canonical owner
    runs ``update``; the other members are views onto its state.

    Args:
        metrics: a Metric/MetricCollection, a sequence of them, or a name->metric dict.
        prefix: string prepended to every result key.
        postfix: string appended to every result key.
        compute_groups: True (discover automatically), False (off), or an
            explicit list of name groups.
        fused_dispatch: None (follow the engine policy — on for accelerator
            backends), or force the one-dispatch fused collection step on/off.
        scan_steps: None (follow the process-wide ``TORCHMETRICS_TPU_SCAN`` /
            ``scan_context`` policy), ``0``/``False`` to force the multi-step
            scan queue off for this collection, or an int K >= 2 to fold K
            collection steps into one donated ``lax.scan`` dispatch
            (``engine/scan.py``).
        async_dispatch: None (follow the process-wide
            ``TORCHMETRICS_TPU_ASYNC`` / ``async_context`` policy),
            ``False``/``0`` to force background drains off, ``True`` / an int
            in-flight bound to drain this collection's scan buffers on the
            background worker (``engine/async_dispatch.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MetricCollection
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassPrecision
        >>> target = jnp.asarray([0, 2, 0, 2, 0, 1, 0, 2])
        >>> preds = jnp.asarray([2, 1, 2, 0, 1, 2, 2, 2])
        >>> metrics = MetricCollection([MulticlassAccuracy(num_classes=3, average='micro'), MulticlassPrecision(num_classes=3, average='macro')])
        >>> result = metrics(preds, target)
        >>> print({k: round(float(v), 4) for k, v in sorted(result.items())})
        {'MulticlassAccuracy': 0.125, 'MulticlassPrecision': 0.0667}
    """

    _groups: Dict[int, _ComputeGroup]
    #: class-level default so unpickled pre-scan instances still resolve policy
    scan_steps: Optional[int] = None
    #: class-level default so unpickled pre-async instances still resolve policy
    async_dispatch: Optional[int] = None

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
        fused_dispatch: Optional[bool] = None,
        scan_steps: Optional[int] = None,
        async_dispatch: Optional[Any] = None,
    ) -> None:
        self._modules: "OrderedDict[str, Metric]" = OrderedDict()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        if fused_dispatch is not None and not isinstance(fused_dispatch, bool):
            raise ValueError(f"Expected `fused_dispatch` to be a bool or None but got {fused_dispatch}")
        self.fused_dispatch = fused_dispatch
        self.scan_steps = scan_steps
        if scan_steps is not None:
            from torchmetrics_tpu.engine.scan import coerce_k

            self.scan_steps = coerce_k(scan_steps)
        self.async_dispatch = async_dispatch
        if async_dispatch is not None:
            from torchmetrics_tpu.engine.async_dispatch import coerce_inflight

            self.async_dispatch = coerce_inflight(async_dispatch)
        self._groups_checked: bool = False
        self._state_is_copy: bool = False
        self._fused_engine = None  # engine/fusion.py executable cache; built lazily
        self._epoch_sync = None  # engine/epoch.py collection-wide packed sync; lazy

        self.add_metrics(metrics, *additional_metrics)

    # ------------------------------------------------------------------ update paths

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Per-metric ``forward`` (batch values); kwargs filtered per signature."""
        self._drain_scan("observation:forward")
        return self._compute_and_reduce("forward", *args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """One collection step: each group owner accumulates the batch once.

        With the fused engine engaged, every owner's update lowers into a single
        shared XLA dispatch; owners the engine cannot compile update eagerly.
        The FIRST step runs every metric individually — group discovery needs
        each metric's own post-update state to prove value equality.
        """
        if self._groups_checked:
            rec = _diag.active_recorder()
            measuring = rec is not None or _profile.active_profile() is not None
            t_step = _perf_counter() if measuring else 0.0
            owners = [(group.owner, self._modules[group.owner]) for group in self._groups.values()]
            from torchmetrics_tpu.engine import txn as _txn

            if _txn.quarantine_error():
                # fail-loud admission for the fused path too: FusedUpdate
                # bypasses the per-metric update wrapper, so the pre-mutation
                # check must run here — before any owner's state can change
                for name, metric in owners:
                    _txn.admission_check_or_raise(metric, args, metric._filter_kwargs(**kwargs))
            handled, scan_active = self._fused_step(owners, args, kwargs)
            eager_donation_possible = False
            for name, metric in owners:
                if name not in handled:
                    if _txn.quarantine_error():
                        # the collection-level pre-check above already admitted
                        # this batch — the per-metric wrapper must not pay a
                        # second blocking device sync for the same inputs
                        metric._admission_prechecked = True
                    metric.update(*args, **metric._filter_kwargs(**kwargs))
                    # a group OWNER queueing through its own per-metric engine
                    # must re-anchor this collection's views when its queue
                    # drains — drains can fire out-of-band (scrapes, scope
                    # exit), where only the hook knows a donation happened.
                    # Wired on QUEUE presence, not the collection-level knob:
                    # a member may queue via its own scan_steps kwarg
                    eng = metric._engine
                    sq = None if eng is None else eng._scan
                    if sq is not None and sq.on_drain is None:
                        sq.on_drain = self._anchor_views_after_scan
                    # engine-off members never donate (harmless True); the
                    # knob is only consulted when the member's engine is on —
                    # the same gating Metric._engine_step applies, so an
                    # invalid env value cannot start raising on engine-off
                    # configurations that never consulted it before
                    if not metric._epoch_enabled() or metric._scan_depth() is None:
                        # this member's EFFECTIVE knob is off (e.g. the
                        # per-metric opt-out under a collection-wide scope):
                        # its step may have been a real donated dispatch
                        eager_donation_possible = True
            if measuring:
                step_us = round((_perf_counter() - t_step) * 1e6, 3)
                _hist.observe(type(self).__name__, "collection", "dispatch_us", step_us)
                if rec is not None:
                    rec.record(
                        "collection.step", type(self).__name__,
                        dispatch_us=step_us, owners=len(owners), fused=len(handled),
                    )
            # with a scan queue active, an update is a pure ENQUEUE: no owner
            # buffer changes until a drain, and every drain re-anchors views
            # itself through the on_drain/on_scan_drain hooks — re-deriving
            # the views per queued step would re-pay exactly the per-step host
            # cost the K-fold exists to amortize. Members whose EFFECTIVE knob
            # is off (per-metric opt-out) may still have donated eagerly this
            # step, so they keep the pre-scan re-anchor behavior
            donated = ((not scan_active) and bool(handled)) or (
                eager_donation_possible
                and any(
                    m._engine is not None and m._engine.stats.donated_dispatches for _, m in owners
                )
            )
            if donated:
                # re-anchor views NOW, not lazily at the next accessor: a donated
                # owner step leaves view members holding DEAD buffers — a metric
                # handle the user retained from an earlier __getitem__ must keep
                # reading valid (fresh) state, exactly as it did pre-donation
                self._state_is_copy = False
                self._materialize_group_views()
            elif self._state_is_copy:
                # eager/undonated path keeps the lazy accessor-time propagation
                self._materialize_group_views()
        else:
            # group discovery needs each metric's own post-update state; run the
            # pass eagerly — compiling a per-metric executable for members that
            # become views (or fused-handled owners) one step later is pure waste
            discovering = bool(self._enable_compute_groups)
            for metric in self.values(copy_state=False):
                if discovering:
                    prior_override = metric.compiled_update
                    metric.compiled_update = False
                try:
                    metric.update(*args, **metric._filter_kwargs(**kwargs))
                finally:
                    if discovering:
                        metric.compiled_update = prior_override
            if self._enable_compute_groups:
                self._discover_groups()
                self._materialize_group_views()
                self._groups_checked = True

    def _fused_step(self, owners: List[Tuple[str, Metric]], args: tuple, kwargs: dict) -> Tuple[set, bool]:
        """Try the one-dispatch fused collection step.

        Returns ``(handled member names, scan_active)`` — the caller needs the
        GATED scan state for its donated-view bookkeeping, and resolving it
        here keeps the env knob unread on engine-off configurations (an
        invalid ``TORCHMETRICS_TPU_SCAN`` must not start raising on setups
        that never consulted it).
        """
        enabled = self.fused_dispatch
        if enabled is None:
            from torchmetrics_tpu.engine.config import engine_enabled

            enabled = engine_enabled()
        k = self._scan_depth() if enabled else None
        fe = self._fused_engine
        stale_engine = fe is not None and [n for n, _ in fe.metrics] != [n for n, _ in owners]
        if fe is not None and (k is None or stale_engine):
            sq = fe._scan
            if sq is not None and sq.pending:
                # leftover payloads — from a closed scan scope, the ENGINE
                # being disabled mid-stream, or an owner-set change about to
                # replace this engine — drain before anything else applies
                # (ordering preserved, nothing orphaned)
                sq.drain("scan-disabled" if not stale_engine else "signature-change")
        if not enabled or len(owners) < 2:
            return set(), k is not None
        if fe is None or stale_engine:
            from torchmetrics_tpu.engine.fusion import FusedUpdate

            fe = self._fused_engine = FusedUpdate(owners)
            # scan drains can fire OUTSIDE collection.update (observation
            # hooks, sidecar scrapes): re-anchor group views the moment a
            # drain donates the owners' buffers, not at the next step
            fe.on_scan_drain = self._anchor_views_after_scan
        if k is not None:
            # async tier resolution mirrors Metric._engine_step: only read
            # where a scan queue is active, so an invalid TORCHMETRICS_TPU_ASYNC
            # cannot raise on configurations that never consulted it
            from torchmetrics_tpu.engine.async_dispatch import resolve_async

            handled = fe.scan_step(args, kwargs, k, resolve_async(self.async_dispatch))
            return (handled if handled is not None else set()), True
        return fe.step(args, kwargs) or set(), False

    def _scan_depth(self) -> Optional[int]:
        """The active scan queue depth for this collection, or None (unqueued)."""
        if self.scan_steps is not None:
            return self.scan_steps or None  # 0 = forced off
        from torchmetrics_tpu.engine.scan import scan_k

        return scan_k()

    def _anchor_views_after_scan(self) -> None:
        if self._groups_checked:
            self._state_is_copy = False
            self._materialize_group_views()

    def _drain_scan(self, reason: str) -> int:
        """Flush every scan queue holding pending steps for ANY member.

        Collection-level observations must drain the fused queue AND any
        per-metric owner queues BEFORE member states are read — and re-anchor
        group views afterwards (a drain donates the owners' buffers, so view
        members would otherwise hold dead arrays).
        """
        from torchmetrics_tpu.engine.scan import flush_metrics

        drained = flush_metrics(list(self._modules.values()), reason)
        if drained:
            self._anchor_views_after_scan()
        return drained

    # ------------------------------------------------------------------ group discovery

    def _discover_groups(self) -> None:
        """Merge groups whose members' states are identical, one pass.

        Candidates bucket by structural fingerprint (pure metadata); within a
        bucket each group folds into the first representative whose state
        VALUES match, else becomes a new representative. Transitive merging
        falls out of representative chaining — no O(n²) rescans.

        Groups that declared a reduction signature were already CSE-merged at
        construction (:meth:`_merge_cse_groups`); here the signature acts as a
        VETO — two groups whose declared reductions differ can never be merged
        by a first-batch value coincidence (e.g. differing ``ignore_index``
        with no ignored label in batch 1). Signature-less groups keep the
        legacy value-equality semantics, including merging with a declared
        group when the values prove equal.
        """
        sigs = self.__dict__.get("_cse_signatures") or {}
        merged: List[_ComputeGroup] = []
        buckets: Dict[tuple, List[_ComputeGroup]] = {}
        for group in self._groups.values():
            owner = self._modules[group.owner]
            fingerprint = _state_fingerprint(owner)
            if fingerprint is None:  # stateless metrics never share a group
                merged.append(group)
                continue
            sig = sigs.get(group.owner)
            for representative in buckets.setdefault(fingerprint, []):
                rep_sig = sigs.get(representative.owner)
                if sig is not None and rep_sig is not None and sig != rep_sig:
                    continue  # declared reductions differ: value match is a coincidence
                if _states_equal(self._modules[representative.owner], owner):
                    representative.absorb(group)
                    break
            else:
                buckets[fingerprint].append(group)
                merged.append(group)
        self._groups = dict(enumerate(merged))
        self._fused_engine = None  # owner set changed; rebuild on next step

    def _materialize_group_views(self, copy: bool = False) -> None:
        """Push canonical (owner) state into every group's view members."""
        if not self._state_is_copy:
            for group in self._groups.values():
                group.materialize_views(self._modules, copy=copy)
        self._state_is_copy = copy

    # retained name for callers/tests written against the reference-era API
    def _compute_groups_create_state_ref(self, copy: bool = False) -> None:
        self._materialize_group_views(copy)

    # ------------------------------------------------------------------ compute

    def compute(self) -> Dict[str, Any]:
        """Per-metric compute into one flat (renamed) dict.

        With the epoch engine engaged (``engine/epoch.py``), every eligible
        compute-group owner syncs up front in ONE packed exchange — a single
        metadata gather + O(dtypes) collectives for the WHOLE collection,
        instead of one collective per state per member — then each member
        computes on the synced canonical states (through its cached compute
        executable) and the owners unsync afterwards.
        """
        self._drain_scan("observation:compute")
        restore = self._packed_epoch_sync()
        try:
            return self._compute_and_reduce("compute")
        finally:
            restore()

    def _packed_epoch_sync(self):
        """Pack-sync the group owners ahead of the member compute pass.

        Returns a restore callable (always safe to call) that re-enables
        per-member auto-sync and unsyncs any owner the member pass left synced.
        """
        enabled = self.fused_dispatch
        if enabled is None:
            from torchmetrics_tpu.engine.config import engine_enabled

            enabled = engine_enabled()

        def noop() -> None:
            return None

        if not enabled:
            return noop
        if self._groups_checked and self._groups:
            owners = [(group.owner, self._modules[group.owner]) for group in self._groups.values()]
        else:
            owners = list(self._modules.items())
        eligible = []
        for name, m in owners:
            # per-metric opt-outs and anything needing special sync semantics
            # (custom gather fn, host states, sub-world groups) sync themselves
            if not m._to_sync or m._is_synced or m.dist_sync_fn is not None:
                continue
            if m.compute_on_cpu or m.compiled_update is False or m.process_group is not None:
                continue
            da = m.distributed_available_fn
            if callable(da) and da():
                eligible.append((name, m))
        if len(eligible) < 2:
            return noop
        from torchmetrics_tpu.engine.epoch import CollectionEpoch

        names = [n for n, _ in eligible]
        if self._epoch_sync is None or self._epoch_sync.names != names:
            self._epoch_sync = CollectionEpoch(names)
        snapshots = {name: m._copy_state_refs() for name, m in eligible}
        if not self._epoch_sync.packed_sync(eligible):
            return noop
        for name, m in eligible:
            m._cache = snapshots[name]
            m._is_synced = True
        # disable auto-sync ONLY for members the packed exchange covered: the
        # synced owners and their group views (which receive the owners' world
        # state). Ineligible members (custom dist_sync_fn, compute_on_cpu,
        # process_group, opt-outs) must keep syncing themselves.
        packed_owners = {name for name, _ in eligible}
        if self._groups_checked and self._groups:
            covered = set()
            for group in self._groups.values():
                if group.owner in packed_owners:
                    covered.update(group.names)
        else:
            covered = packed_owners
        disabled = []
        for name, m in self._modules.items():
            if name in covered and m._to_sync:
                m._to_sync = False
                disabled.append(m)
        self._state_is_copy = False  # re-anchor views onto the synced owners

        def restore() -> None:
            for m in disabled:
                m._to_sync = True
            for _, m in eligible:
                if m._is_synced:  # a member pass normally unsyncs owners itself
                    m.unsync()
            self._state_is_copy = False  # next accessor re-anchors local state

        return restore

    def _compute_and_reduce(self, method_name: str, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        if method_name not in ("compute", "forward"):
            raise ValueError(f"method_name should be either 'compute' or 'forward', but got {method_name}")
        result = {}
        for name, metric in self.items(keep_base=True, copy_state=False):
            if method_name == "compute":
                res = metric.compute()
            else:
                res = metric(*args, **metric._filter_kwargs(**kwargs))
            if isinstance(res, dict):
                for key, value in res.items():
                    if getattr(metric, "prefix", None) is not None:
                        key = f"{metric.prefix}{key}"
                    if getattr(metric, "postfix", None) is not None:
                        key = f"{key}{metric.postfix}"
                    result[key] = value
            else:
                result[name] = res
        return {self._set_name(k): v for k, v in result.items()}

    # ------------------------------------------------------------------ lifecycle

    def reset(self) -> None:
        """Reset every metric; group views re-anchor to the (reset) owners."""
        from torchmetrics_tpu.engine.scan import discard_metrics

        # the fused queue's payloads die with the reset, undispatched —
        # byte-identical to folding then wiping (member resets discard theirs)
        discard_metrics(list(self._modules.values()), "reset")
        for metric in self.values(copy_state=False):
            metric.reset()
        if self._enable_compute_groups and self._groups_checked:
            self._materialize_group_views()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        """Deep copy, optionally re-prefixed."""
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def __getstate__(self) -> Dict[str, Any]:
        """Compiled fused executables are per-process — never pickled/copied."""
        # the fused engine (and its scan queue) is dropped below: pending
        # payloads must fold into the owners' states first, or the copy lags
        self._drain_scan("observation:clone")
        state = self.__dict__.copy()
        state["_fused_engine"] = None
        state["_epoch_sync"] = None
        return state

    def persistent(self, mode: bool = True) -> None:
        """Toggle state persistence for all metrics."""
        for metric in self.values(copy_state=False):
            metric.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        """Flat state dict keyed by metric name."""
        self._drain_scan("observation:state_dict")
        destination: Dict[str, Any] = {}
        for name, metric in self.items(keep_base=True, copy_state=False):
            metric.state_dict(destination, prefix=f"{name}.")
        return destination

    def state_footprint(self) -> Dict[str, Any]:
        """Live HBM bytes held by member states, deduplicating the buffers
        compute-group view members share with their owner (``unique_bytes`` is
        what the device actually holds; ``shared_bytes`` is the view overlap).
        See ``torchmetrics_tpu.diag.costs.state_footprint``."""
        self._materialize_group_views()
        from torchmetrics_tpu.diag.costs import state_footprint

        return state_footprint(self)

    def snapshot_compute(self) -> Dict[str, Any]:
        """Scrape-anytime per-member ``compute()`` on shielded state copies.

        The collection-level analogue of :meth:`Metric.snapshot_compute`:
        every member's value computes off a donation-proof snapshot (group
        views materialized first, so view members hold real arrays), the hot
        loop keeps updating, and no member syncs or caches. Rank-local.
        """
        self._drain_scan("observation:snapshot")
        self._materialize_group_views()
        from torchmetrics_tpu.serve.snapshot import snapshot_compute

        return {
            name: snapshot_compute(metric)
            for name, metric in self.items(copy_state=False)
        }

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        """Restore from ``state_dict``."""
        for name, metric in self.items(keep_base=True, copy_state=False):
            metric.load_state_dict(state_dict, prefix=f"{name}.")

    # ------------------------------------------------------------------ membership

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Register metrics from dict/sequence/instance."""
        # membership change drops the fused engine below — its scan queue's
        # enqueued payloads must fold into the existing members' states first
        # (the __getstate__ precedent), or they are lost to GC while the
        # members' update counts stay advanced
        self._drain_scan("observation:membership-change")
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            metrics = list(metrics)
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                rank_zero_warn(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of"
                        " `torchmetrics_tpu.Metric` or `torchmetrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        v.postfix = metric.postfix
                        v.prefix = metric.prefix
                        self._modules[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of"
                        " `torchmetrics_tpu.Metric` or `torchmetrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = metric.__class__.__name__
                    if name in self._modules:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        v.postfix = metric.postfix
                        v.prefix = metric.prefix
                        self._modules[k] = v
        else:
            raise ValueError(
                "Unknown input to MetricCollection. Expected, `Metric`, `MetricCollection` or `dict`/`sequence` of the"
                f" previous, but got {metrics}"
            )

        self._groups_checked = False
        self._fused_engine = None
        if self._enable_compute_groups:
            self._init_compute_groups()
        else:
            self._groups = {}

    def _init_compute_groups(self) -> None:
        """Seed groups: user-specified lists, or one singleton per metric
        (then CSE-merged by declared reduction signature)."""
        if isinstance(self._enable_compute_groups, list):
            for names in self._enable_compute_groups:
                for metric in names:
                    if metric not in self._modules:
                        raise ValueError(
                            f"Input {metric} in `compute_groups` argument does not match a metric in the"
                            f" collection. Please make sure that {self._enable_compute_groups} matches"
                            f" {list(self._modules.keys())}"
                        )
            self._groups = {i: _ComputeGroup(names) for i, names in enumerate(self._enable_compute_groups)}
            self._groups_checked = True
        else:
            self._groups = {i: _ComputeGroup([str(k)]) for i, k in enumerate(self._modules.keys())}
            self._merge_cse_groups()

    def _merge_cse_groups(self) -> None:
        """Cross-metric common-subexpression fusion at CONSTRUCTION time.

        Metrics declaring an equal :func:`~torchmetrics_tpu.engine.statespec.
        reduction_signature` — the stat-scores family with matching
        task/num_classes/top_k/ignore_index knobs, confusion matrices with
        matching shape knobs — provably run one identical state-producing
        reduction, so they merge into one compute group NOW: the shared
        TP/FP/TN/FN (or confmat) reduction traces once into one canonical
        donated state, and every member derives its compute from the shared
        buffers.

        When EVERY member carries a signature, discovery is complete here —
        the first step is already fused (no N-way eager discovery pass, no
        sanctioned host readback for value comparison). A mix of declared and
        undeclared members keeps the legacy first-step value-equality pass for
        the undeclared ones, with the signatures acting as a merge veto
        (:meth:`_discover_groups`). ``TORCHMETRICS_TPU_CSE=0`` opts out
        entirely.
        """
        from torchmetrics_tpu.engine.statespec import cse_enabled, reduction_signature

        if not cse_enabled():
            self._cse_signatures = {}
            return
        sigs = {name: reduction_signature(m) for name, m in self._modules.items()}
        self._cse_signatures = sigs
        # an equal signature proves IDENTICAL update bodies, not identical
        # accumulated state: only metrics still at their registered defaults
        # may merge declaratively (a late-added or pre-updated metric carries
        # state the others never saw — it keeps the legacy value-equality
        # path, which correctly refuses the merge)
        fresh = {
            name: self._metric_state_is_default(m) for name, m in self._modules.items()
        }
        merged: List[_ComputeGroup] = []
        by_sig: Dict[tuple, _ComputeGroup] = {}
        for group in self._groups.values():
            sig = sigs.get(group.owner)
            if sig is None or not fresh.get(group.owner, False):
                merged.append(group)
                continue
            representative = by_sig.get(sig)
            if representative is None:
                by_sig[sig] = group
                merged.append(group)
            else:
                representative.absorb(group)
        self._groups = dict(enumerate(merged))
        if self._groups and all(
            sigs[name] is not None and fresh[name] for name in self._modules
        ):
            # every member declared its reduction and stands at defaults:
            # discovery is DONE — the first step runs fused, and the one-time
            # value-comparison host readback of the legacy pass never happens
            self._groups_checked = True
            self._materialize_group_views()

    @staticmethod
    def _metric_state_is_default(metric: Metric) -> bool:
        """Pure host-side identity check: never updated, never synced, every
        array state still IS its registered default (no device traffic)."""
        if metric._update_count != 0 or metric._is_synced:
            return False
        for attr, default in metric._defaults.items():
            value = getattr(metric, attr)
            if isinstance(default, list) or isinstance(value, list):
                if value:
                    return False
            elif value is not default:
                return False
        return True

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        """Current compute groups as ``{index: [member names]}``."""
        return {i: list(group.names) for i, group in self._groups.items()}

    # ------------------------------------------------------------------ dict protocol

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def _to_renamed_ordered_dict(self) -> OrderedDict:
        od = OrderedDict()
        for k, v in self._modules.items():
            od[self._set_name(k)] = v
        return od

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def __len__(self) -> int:
        return len(self._modules)

    def __contains__(self, key: str) -> bool:
        return key in self._modules

    def keys(self, keep_base: bool = False) -> Iterable[Hashable]:
        """Metric names (renamed unless ``keep_base``)."""
        if keep_base:
            return self._modules.keys()
        return self._to_renamed_ordered_dict().keys()

    def items(self, keep_base: bool = False, copy_state: bool = True) -> Iterable[Tuple[str, Metric]]:
        """(name, metric) pairs; materializes group views first."""
        self._materialize_group_views(copy_state)
        if keep_base:
            return self._modules.items()
        return self._to_renamed_ordered_dict().items()

    def values(self, copy_state: bool = True) -> Iterable[Metric]:
        """Metrics; materializes group views first."""
        self._materialize_group_views(copy_state)
        return self._modules.values()

    def __getitem__(self, key: str, copy_state: bool = True) -> Metric:
        """Metric by (renamed) key."""
        self._materialize_group_views(copy_state)
        if self.prefix or self.postfix:
            key = key.removeprefix(self.prefix or "").removesuffix(self.postfix or "")
        return self._modules[key]

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for k, v in self._modules.items():
            repr_str += f"\n  {k}: {v!r}"
        if self.prefix:
            repr_str += f",\n  prefix={self.prefix}"
        if self.postfix:
            repr_str += f",\n  postfix={self.postfix}"
        return repr_str + "\n)"

    def set_dtype(self, dst_type: Any) -> "MetricCollection":
        """Cast all metric states."""
        for metric in self.values(copy_state=False):
            metric.set_dtype(dst_type)
        return self

    def to(self, device: Any) -> "MetricCollection":
        """Move all metric states to ``device``."""
        for metric in self.values(copy_state=False):
            metric.to(device)
        return self

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None, together: bool = False) -> Any:
        """Plot all metrics, together or one figure each."""
        import matplotlib.pyplot as plt

        if val is None:
            val = self.compute()
        if together:
            from torchmetrics_tpu.utilities.plot import plot_single_or_multi_val

            return plot_single_or_multi_val(val, ax=ax)
        fig_axs = []
        for k, m in self.items(keep_base=False, copy_state=False):
            f, a = m.plot(val[k] if isinstance(val, dict) and k in val else None)
            fig_axs.append((f, a))
        del plt
        return fig_axs
