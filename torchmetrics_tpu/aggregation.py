"""Scalar-stream aggregators with NaN policy.

Capability parity: reference ``src/torchmetrics/aggregation.py`` (``BaseAggregator:30``,
``MaxMetric:100``, ``MinMetric:200``, ``SumMetric:300``, ``CatMetric:399``,
``MeanMetric:459``, ``RunningMean:573``, ``RunningSum:629``).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.prints import rank_zero_warn
from torchmetrics_tpu.wrappers.running import Running

Array = jax.Array


class BaseAggregator(Metric):
    """Base aggregator: one ``value`` state + NaN strategy (reference ``aggregation.py:30-97``).

    ``nan_strategy``: ``'error'`` raises, ``'warn'`` warns and removes, ``'ignore'``
    silently removes, a float imputes.
    """

    value: Array
    is_differentiable = None
    higher_is_better = None
    full_state_update: bool = False

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy}"
                f" but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.add_state("value", default=default_value, dist_reduce_fx=fn)

    def _cast_and_nan_check_input(self, x: Union[float, Array]) -> Array:
        """To float array + NaN policy (reference ``aggregation.py:70-97``).

        NaN detection/removal is an eager host-side step (aggregator updates are tiny);
        the float-impute path stays branch-free device code.
        """
        x = jnp.asarray(x, dtype=jnp.float32)
        if isinstance(self.nan_strategy, float):
            return jnp.nan_to_num(x, nan=self.nan_strategy)
        nans = np.isnan(np.asarray(x))
        if nans.any():
            if self.nan_strategy == "error":
                raise RuntimeError("Encounted `nan` values in tensor")
            if self.nan_strategy == "warn":
                rank_zero_warn("Encounted `nan` values in tensor. Will be removed.", UserWarning)
            x = jnp.asarray(np.asarray(x).flatten()[~nans.flatten()], dtype=jnp.float32)
        return x

    def update(self, value: Union[float, Array]) -> None:
        """Overwrite in child class."""

    def compute(self) -> Array:
        """Return the aggregated value."""
        return self.value

    def plot(self, val: Optional[Union[Array, Sequence[Array]]] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class MaxMetric(BaseAggregator):
    """Running max of a value stream (reference ``aggregation.py:100``)."""

    full_state_update: bool = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.asarray(-jnp.inf, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        """Fold batch max into state."""
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = jnp.maximum(self.value, jnp.max(value))


class MinMetric(BaseAggregator):
    """Running min of a value stream (reference ``aggregation.py:200``)."""

    full_state_update: bool = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        """Fold batch min into state."""
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = jnp.minimum(self.value, jnp.min(value))


class SumMetric(BaseAggregator):
    """Running sum of a value stream (reference ``aggregation.py:300``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SumMetric
        >>> metric = SumMetric()
        >>> metric.update(jnp.asarray([1.0, 2.0, 3.0]))
        >>> print(float(metric.compute()))
        6.0
    """

    #: the update is additive in its sum-reduced state (``new = old + g(batch)``)
    #: — the contract the compensated accumulation (engine/numerics.py) relies
    #: on to recover the pure batch contribution from a zeroed state
    _engine_state_additive = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0, dtype=jnp.float32), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        """Add batch sum into state."""
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value = self.value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate all seen values (reference ``aggregation.py:399``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        """Append batch values."""
        value = self._cast_and_nan_check_input(value)
        if value.size:
            self.value.append(value)

    def compute(self) -> Array:
        """Concatenated values."""
        if isinstance(self.value, list) and self.value:
            return jnp.concatenate([jnp.atleast_1d(v) for v in self.value])
        return self.value


class MeanMetric(BaseAggregator):
    """Weighted running mean (reference ``aggregation.py:459-560``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanMetric
        >>> metric = MeanMetric()
        >>> metric.update(1.0)
        >>> metric.update(jnp.asarray([2.0, 3.0]))
        >>> print(float(metric.compute()))
        2.0
    """

    weight: Array

    #: additive in both sum-reduced states — compensation-eligible (numerics.py)
    _engine_state_additive = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0, dtype=jnp.float32), nan_strategy, **kwargs)
        self.add_state("weight", default=jnp.asarray(0.0, dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        """Accumulate weighted sum + weight total; ``weight`` broadcasts to ``value``."""
        value = self._cast_and_nan_check_input(value)
        weight = self._cast_and_nan_check_input(weight)
        if value.size == 0:
            return
        weight = jnp.broadcast_to(weight, value.shape)
        self.value = self.value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        """Weighted mean."""
        return self.value / self.weight


class RunningMean(Running):
    """Mean over a running window (reference ``aggregation.py:573``)."""

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(base_metric=MeanMetric(nan_strategy=nan_strategy, **kwargs), window=window)


class RunningSum(Running):
    """Sum over a running window (reference ``aggregation.py:629``).
    """

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(base_metric=SumMetric(nan_strategy=nan_strategy, **kwargs), window=window)
