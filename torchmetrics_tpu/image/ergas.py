"""Modular ERGAS (reference ``src/torchmetrics/image/ergas.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax

from torchmetrics_tpu.functional.image.ergas import _ergas_compute, _ergas_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class ErrorRelativeGlobalDimensionlessSynthesis(Metric):
    """ERGAS (reference ``ergas.py:26-119``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.image.ergas import ErrorRelativeGlobalDimensionlessSynthesis
        >>> metric = ErrorRelativeGlobalDimensionlessSynthesis()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        63.5037
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    preds: List[Array]
    target: List[Array]

    def __init__(
        self,
        ratio: Union[int, float] = 4,
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.ratio = ratio
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Buffer one batch of image pairs."""
        preds, target = _ergas_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """ERGAS over all buffered images."""
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _ergas_compute(preds, target, self.ratio, self.reduction)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
