"""Modular RASE (reference ``src/torchmetrics/image/rase.py``)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from torchmetrics_tpu.functional.image.rase import _rase_compute, _rase_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class RelativeAverageSpectralError(Metric):
    """RASE (reference ``rase.py:25-108``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.image.rase import RelativeAverageSpectralError
        >>> metric = RelativeAverageSpectralError()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        1024.0444
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    preds: List[Array]
    target: List[Array]

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError(f"Argument `window_size` is expected to be a positive integer, but got {window_size}")
        self.window_size = window_size
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Buffer one batch of image pairs."""
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """RASE over all buffered images."""
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        rmse_map, target_sum, total_images = _rase_update(
            preds, target, self.window_size, rmse_map=None, target_sum=None, total_images=None
        )
        return _rase_compute(rmse_map, target_sum, total_images, self.window_size)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
