"""Modular TotalVariation (reference ``src/torchmetrics/image/tv.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.tv import _total_variation_compute, _total_variation_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class TotalVariation(Metric):
    """TV (reference ``tv.py:26-113``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import TotalVariation
        >>> img = jnp.arange(16.0).reshape(1, 1, 4, 4)
        >>> metric = TotalVariation()
        >>> print(float(metric(img)))
        60.0
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction is not None and reduction not in ("sum", "mean", "none"):
            raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")
        self.reduction = reduction

        if self.reduction is None or self.reduction == "none":
            self.add_state("score", [], dist_reduce_fx="cat")
        else:
            self.add_state("score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num_elements", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, img: Array) -> None:
        """Accumulate per-image TV."""
        score, num_elements = _total_variation_update(img)
        if self.reduction is None or self.reduction == "none":
            self.score.append(score)
        else:
            self.score = self.score + score.sum()
        self.num_elements = self.num_elements + num_elements

    def compute(self) -> Union[Array, List[Array]]:
        """Reduced TV."""
        if self.reduction is None or self.reduction == "none":
            return dim_zero_cat(self.score)
        return _total_variation_compute(jnp.atleast_1d(self.score), self.num_elements, self.reduction)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
