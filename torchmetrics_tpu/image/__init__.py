"""Modular image metrics (reference ``src/torchmetrics/image/__init__.py``)."""

from torchmetrics_tpu.image.d_lambda import SpectralDistortionIndex
from torchmetrics_tpu.image.ergas import ErrorRelativeGlobalDimensionlessSynthesis
from torchmetrics_tpu.image.fid import FrechetInceptionDistance
from torchmetrics_tpu.image.inception import InceptionScore
from torchmetrics_tpu.image.kid import KernelInceptionDistance
from torchmetrics_tpu.image.lpip import LearnedPerceptualImagePatchSimilarity
from torchmetrics_tpu.image.psnr import PeakSignalNoiseRatio
from torchmetrics_tpu.image.psnrb import PeakSignalNoiseRatioWithBlockedEffect
from torchmetrics_tpu.image.rase import RelativeAverageSpectralError
from torchmetrics_tpu.image.rmse_sw import RootMeanSquaredErrorUsingSlidingWindow
from torchmetrics_tpu.image.sam import SpectralAngleMapper
from torchmetrics_tpu.image.ssim import (
    MultiScaleStructuralSimilarityIndexMeasure,
    StructuralSimilarityIndexMeasure,
)
from torchmetrics_tpu.image.tv import TotalVariation
from torchmetrics_tpu.image.uqi import UniversalImageQualityIndex

__all__ = [
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "FrechetInceptionDistance",
    "InceptionScore",
    "KernelInceptionDistance",
    "LearnedPerceptualImagePatchSimilarity",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
]
