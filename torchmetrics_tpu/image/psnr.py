"""Modular PSNR (reference ``src/torchmetrics/image/psnr.py``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.psnr import _psnr_compute, _psnr_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class PeakSignalNoiseRatio(Metric):
    """PSNR (reference ``psnr.py:28-160``).

    Scalar sum states when ``dim`` is None; cat list states of per-slice SSE/count
    otherwise. When ``data_range`` is None the observed min/max are tracked as
    min/max-reduced states.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import PeakSignalNoiseRatio
        >>> psnr = PeakSignalNoiseRatio()
        >>> preds = jnp.asarray([[0.0, 1.0], [2.0, 3.0]])
        >>> target = jnp.asarray([[3.0, 2.0], [1.0, 0.0]])
        >>> print(round(float(psnr(preds, target)), 4))
        2.5527
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        base: float = 10.0,
        reduction: Optional[str] = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if dim is None and reduction != "elementwise_mean":
            from torchmetrics_tpu.utilities.prints import rank_zero_warn

            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")

        if dim is None:
            self.add_state("sum_squared_error", jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", [], dist_reduce_fx="cat")
            self.add_state("total", [], dist_reduce_fx="cat")

        self.clamping_fn = None
        self._track_range = data_range is None
        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.add_state("min_target", jnp.asarray(0.0), dist_reduce_fx="min")
            self.add_state("max_target", jnp.asarray(0.0), dist_reduce_fx="max")
        elif isinstance(data_range, tuple):
            self.add_state("data_range", jnp.asarray(float(data_range[1] - data_range[0])), dist_reduce_fx="mean")
            self.clamping_fn = lambda x: jnp.clip(x, data_range[0], data_range[1])
        else:
            self.add_state("data_range", jnp.asarray(float(data_range)), dist_reduce_fx="mean")
        self.base = base
        self.reduction = reduction
        self.dim = dim

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate SSE/count (+ observed range when tracking it)."""
        if self.clamping_fn is not None:
            preds = self.clamping_fn(preds)
            target = self.clamping_fn(target)

        sum_squared_error, n_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            if self._track_range:
                self.min_target = jnp.minimum(target.min(), self.min_target)
                self.max_target = jnp.maximum(target.max(), self.max_target)
            self.sum_squared_error = self.sum_squared_error + sum_squared_error
            self.total = self.total + n_obs
        else:
            self.sum_squared_error.append(sum_squared_error)
            self.total.append(n_obs)

    def compute(self) -> Array:
        """PSNR over the accumulated error."""
        data_range = self.max_target - self.min_target if self._track_range else self.data_range
        if self.dim is None:
            sum_squared_error = self.sum_squared_error
            total = self.total
        else:
            sum_squared_error = dim_zero_cat(self.sum_squared_error)
            total = dim_zero_cat(self.total)
        return _psnr_compute(sum_squared_error, total, data_range, base=self.base, reduction=self.reduction)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
