"""Inception Score (reference ``src/torchmetrics/image/inception.py``).

List state of logits features (``dist_reduce_fx=None`` — raw gather at sync, like the
reference ``inception.py:140``); split-KL computed at epoch end.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.image._extractor import resolve_feature_extractor
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.compute import _safe_xlogy
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


class InceptionScore(Metric):
    """IS = exp(E[KL(p(y|x) ‖ p(y))]) over splits (reference ``inception.py:30-185``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    features: List[Array]

    def __init__(
        self,
        feature: Union[str, int, Callable[[Array], Array]] = "logits_unbiased",
        splits: int = 10,
        normalize: bool = False,
        num_features: Optional[int] = None,
        allow_random_features: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `InceptionScore` will save all extracted features in buffer."
            " For large datasets this may lead to large memory footprint.",
            UserWarning,
        )
        self.inception, _ = resolve_feature_extractor(
            feature, num_features, allow_random_features=allow_random_features
        )
        if not (isinstance(splits, int) and splits > 0):
            raise ValueError("Integer input to argument `splits` must be positive")
        self.splits = splits
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self.add_state("features", [], dist_reduce_fx=None)

    def update(self, imgs: Array) -> None:
        """Extract and buffer logits (reference ``inception.py:152-156``)."""
        imgs = (imgs * 255).astype(jnp.uint8) if self.normalize else imgs
        features = self.inception(imgs)
        self.features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        """Mean/std of per-split exp(KL) (reference ``inception.py:158-180``)."""
        features = dim_zero_cat(self.features)
        # random permutation on host — compute runs once per epoch
        idx = np.random.permutation(features.shape[0])
        features = features[jnp.asarray(idx)]

        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)

        prob_chunks = jnp.array_split(prob, self.splits, axis=0)
        log_prob_chunks = jnp.array_split(log_prob, self.splits, axis=0)

        kl_ = []
        for p, log_p in zip(prob_chunks, log_prob_chunks):
            mean_prob = p.mean(axis=0, keepdims=True)
            # p*log_p uses the finite log_softmax; the marginal term goes through
            # xlogy so classes whose probability underflows to exactly 0 contribute
            # 0 instead of 0 * log(0) = nan (hit with saturated/extreme logits)
            kl = p * log_p - _safe_xlogy(p, jnp.broadcast_to(mean_prob, p.shape))
            kl_.append(jnp.exp(kl.sum(axis=1).mean()))
        kl_stack = jnp.stack(kl_)
        return kl_stack.mean(), kl_stack.std(ddof=1)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        val = val if val is not None else self.compute()[0]
        return self._plot(val, ax)
