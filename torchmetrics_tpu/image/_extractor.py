"""Pluggable feature-extractor resolution for model-backed image metrics.

The reference builds its extractors from ``torch-fidelity``'s pretrained InceptionV3
(``image/fid.py:52-157``). This environment has no bundled weights and no egress, so
the extractor is an injection point instead: any callable ``imgs -> (N, d) features``
(a Flax module's apply, a jitted function, …). Passing the reference's integer feature
sizes raises the same kind of actionable error the reference raises when
``torch-fidelity`` is missing.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def resolve_feature_extractor(
    feature,
    num_features: Optional[int] = None,
    probe_shape: Tuple[int, ...] = (1, 3, 299, 299),
) -> Tuple[Callable[[Array], Array], int]:
    """Return ``(extractor, num_features)`` for a pluggable ``feature`` argument.

    Args:
        feature: a callable ``imgs -> (N, d)`` feature extractor, or one of the
            reference's integer/str defaults (which require pretrained weights and
            therefore raise here with guidance).
        num_features: feature dimensionality; probed with a dummy forward if ``None``.
        probe_shape: shape of the dummy input used to probe ``num_features``.
    """
    if isinstance(feature, (int, str)):
        raise ModuleNotFoundError(
            f"Default feature extractor `feature={feature!r}` requires pretrained InceptionV3 weights, which are"
            " not bundled. Build one with `torchmetrics_tpu.models.inception_v3_extractor(state_dict=...)`"
            " from a torchvision inception_v3 checkpoint (the architecture is a native Flax module), or pass"
            " any callable `imgs -> (N, d)` feature extractor. Note: that trunk ends at the 2048-d pool —"
            " InceptionScore needs class LOGITS, so wrap the trunk with the checkpoint's fc layer."
        )
    if not callable(feature):
        raise TypeError("Got unknown input to argument `feature`")
    if num_features is None:
        probe = jnp.zeros(probe_shape, dtype=jnp.uint8)
        num_features = int(feature(probe).shape[-1])
    return feature, num_features
