"""Pluggable feature-extractor resolution for model-backed image metrics.

The reference builds its extractors from ``torch-fidelity``'s pretrained InceptionV3
(``image/fid.py:52-157``). The TPU build ships that trunk as a native Flax module —
``models.inception.FIDInceptionV3`` reproduces the FID-variant pooling blocks, the
TF1-style bilinear resize to 299x299, and the 1008-way logits head — so the
reference's integer/str defaults (``feature=64/192/768/2048``, ``'logits_unbiased'``)
work out of the box once weights are supplied. Pretrained weights are NOT bundled
(zero-egress environment): without them the builder RAISES unless the caller opts in
with ``allow_random_features=True``, in which case the trunk is deterministically
randomly initialised and warns — scores are then self-consistent but not canonical
until a ``pt_inception-2015-12-05`` checkpoint is converted in. Any callable
``imgs -> (N, d)`` remains accepted as a custom extractor.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

_FID_TAP_DIMS = {"64": 64, "192": 192, "768": 768, "2048": 2048, "logits_unbiased": 1008, "logits": 1008}


def resolve_feature_extractor(
    feature,
    num_features: Optional[int] = None,
    probe_shape: Tuple[int, ...] = (1, 3, 299, 299),
    allow_random_features: bool = False,
) -> Tuple[Callable[[Array], Array], int]:
    """Return ``(extractor, num_features)`` for a pluggable ``feature`` argument.

    Args:
        feature: one of the reference's integer/str taps (64/192/768/2048 /
            'logits_unbiased'/'logits' — builds the FID-compat InceptionV3 trunk,
            reference ``image/fid.py:186-201``), or a callable ``imgs -> (N, d)``.
        num_features: feature dimensionality; for callables probed with a dummy
            forward when ``None``.
        probe_shape: shape of the dummy input used to probe ``num_features``.
        allow_random_features: opt-in for the randomly-initialised built-in trunk
            when no weights are available; without it the builder raises (matching
            the reference's hard error when torch-fidelity is absent,
            ``image/fid.py:264-270``).
    """
    if isinstance(feature, (int, str)):
        tap = str(feature)
        if tap not in _FID_TAP_DIMS:
            raise ValueError(
                f"Integer/str input to argument `feature` must be one of {sorted(_FID_TAP_DIMS)}, got {feature!r}"
            )
        from torchmetrics_tpu.models.inception import fid_inception_v3_extractor

        return fid_inception_v3_extractor(tap, allow_random=allow_random_features), _FID_TAP_DIMS[tap]
    if not callable(feature):
        raise TypeError("Got unknown input to argument `feature`")
    if num_features is None:
        probe = jnp.zeros(probe_shape, dtype=jnp.uint8)
        num_features = int(feature(probe).shape[-1])
    return feature, num_features
