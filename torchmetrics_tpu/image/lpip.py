"""Modular LPIPS (reference ``src/torchmetrics/image/lpip.py``).

Sum-of-distances + count states. String ``net_type`` works out of the box: the learned
LPIPS heads are bundled (converted from the reference's ``lpips_models/*.pth``); the
backbone is a native Flax module — deterministically random-initialised (with a
warning) unless ``backbone_state_dict``/``backbone_variables`` supplies torchvision
ImageNet weights, in which case values are canonical LPIPS.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.lpips import _lpips_compute, _lpips_update, lpips_network
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class LearnedPerceptualImagePatchSimilarity(Metric):
    """LPIPS (reference ``lpip.py:30-142``).

    Args:
        net_type: ``'alex'``/``'vgg'``/``'squeeze'`` (bundled learned heads + native
            Flax backbone; backbone weights random-init with a warning unless supplied
            below), or a ``net(img1, img2, normalize=...) -> (N,)`` callable built with
            :func:`torchmetrics_tpu.functional.image.lpips.make_lpips_net`.
        reduction: 'mean' or 'sum' over accumulated per-sample distances.
        normalize: True if inputs are in [0,1] (scaled to [-1,1] internally).
        backbone_state_dict: torchvision checkpoint for the string backbone — supplies
            ImageNet weights, making values canonical LPIPS.
        backbone_variables: ready flax variables for the string backbone.
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        net_type: Union[str, Callable[..., Array]] = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        backbone_state_dict: Optional[Any] = None,
        backbone_variables: Optional[Any] = None,
        allow_random_backbone: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if isinstance(net_type, str):
            valid_net_type = ("vgg", "alex", "squeeze")
            if net_type not in valid_net_type:
                raise ValueError(f"Argument `net_type` must be one of {valid_net_type}, but got {net_type}.")
            self.net = lpips_network(
                net_type,
                backbone_state_dict=backbone_state_dict,
                backbone_variables=backbone_variables,
                allow_random_backbone=allow_random_backbone,
            )
        elif callable(net_type):
            self.net = net_type
        else:
            raise ValueError("Argument `net_type` must be a string or a callable net.")

        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.reduction = reduction

        if not isinstance(normalize, bool):
            raise ValueError(f"Argument `normalize` should be an bool but got {normalize}")
        self.normalize = normalize

        self.add_state("sum_scores", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:
        """Accumulate per-batch LPIPS distances."""
        loss, total = _lpips_update(img1, img2, net=self.net, normalize=self.normalize)
        self.sum_scores = self.sum_scores + loss.sum()
        self.total = self.total + total

    def compute(self) -> Array:
        """Reduced LPIPS."""
        return _lpips_compute(self.sum_scores, self.total, self.reduction)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
