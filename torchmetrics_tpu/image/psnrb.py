"""Modular PSNR-B (reference ``src/torchmetrics/image/psnrb.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.psnrb import _psnrb_compute, _psnrb_update
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class PeakSignalNoiseRatioWithBlockedEffect(Metric):
    """PSNR-B for grayscale images (reference ``psnrb.py:25-104``)."""

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, block_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(block_size, int) or block_size < 1:
            raise ValueError("Argument `block_size` should be a positive integer")
        self.block_size = block_size
        self.add_state("sum_squared_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("bef", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("data_range", jnp.asarray(0.0), dist_reduce_fx="max")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate SSE, blocking effect, count, observed range."""
        sum_squared_error, bef, n_obs = _psnrb_update(preds, target, block_size=self.block_size)
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.bef = self.bef + bef
        self.total = self.total + n_obs
        self.data_range = jnp.maximum(self.data_range, target.max() - target.min())

    def compute(self) -> Array:
        """PSNR-B over accumulated statistics."""
        return _psnrb_compute(self.sum_squared_error, self.bef, self.total, self.data_range)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
