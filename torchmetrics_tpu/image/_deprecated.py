"""Deprecated-root-import shims (reference ``image/_deprecated.py``)."""

from torchmetrics_tpu.image import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    RelativeAverageSpectralError,
    RootMeanSquaredErrorUsingSlidingWindow,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    TotalVariation,
    UniversalImageQualityIndex,
)
from torchmetrics_tpu.utilities.deprecation import root_alias

_ErrorRelativeGlobalDimensionlessSynthesis = root_alias(ErrorRelativeGlobalDimensionlessSynthesis, "image")
_MultiScaleStructuralSimilarityIndexMeasure = root_alias(MultiScaleStructuralSimilarityIndexMeasure, "image")
_PeakSignalNoiseRatio = root_alias(PeakSignalNoiseRatio, "image")
_RelativeAverageSpectralError = root_alias(RelativeAverageSpectralError, "image")
_RootMeanSquaredErrorUsingSlidingWindow = root_alias(RootMeanSquaredErrorUsingSlidingWindow, "image")
_SpectralAngleMapper = root_alias(SpectralAngleMapper, "image")
_SpectralDistortionIndex = root_alias(SpectralDistortionIndex, "image")
_StructuralSimilarityIndexMeasure = root_alias(StructuralSimilarityIndexMeasure, "image")
_TotalVariation = root_alias(TotalVariation, "image")
_UniversalImageQualityIndex = root_alias(UniversalImageQualityIndex, "image")
