"""Modular UQI (reference ``src/torchmetrics/image/uqi.py``).

Cat list states of raw images, like the reference (``uqi.py:92-93``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax

from torchmetrics_tpu.functional.image.uqi import _uqi_compute, _uqi_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class UniversalImageQualityIndex(Metric):
    """UQI (reference ``uqi.py:26-121``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.image.uqi import UniversalImageQualityIndex
        >>> metric = UniversalImageQualityIndex()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.9589
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds: List[Array]
    target: List[Array]

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Buffer one batch of image pairs."""
        preds, target = _uqi_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """UQI over all buffered images."""
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _uqi_compute(preds, target, self.kernel_size, self.sigma, self.reduction)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
