"""Kernel Inception Distance (reference ``src/torchmetrics/image/kid.py``).

Raw feature list states (``dist_reduce_fx=None``); polynomial-kernel MMD over random
subsets at compute. All subset MMDs are evaluated as one vmapped batch of kernel
matmuls — MXU-friendly — instead of the reference's Python loop (``kid.py:...``).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.image._extractor import resolve_feature_extractor
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def poly_kernel(f1: Array, f2: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> Array:
    """Polynomial kernel matrix (reference ``kid.py:36-41``)."""
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (f1 @ f2.T * gamma + coef) ** degree


def maximum_mean_discrepancy(k_xx: Array, k_xy: Array, k_yy: Array) -> Array:
    """Unbiased MMD estimate from kernel matrices (reference ``kid.py:17-33``)."""
    m = k_xx.shape[0]
    diag_x = jnp.diag(k_xx)
    diag_y = jnp.diag(k_yy)
    kt_xx_sum = (k_xx.sum(axis=-1) - diag_x).sum()
    kt_yy_sum = (k_yy.sum(axis=-1) - diag_y).sum()
    k_xy_sum = k_xy.sum()
    value = (kt_xx_sum + kt_yy_sum) / (m * (m - 1))
    return value - 2 * k_xy_sum / (m**2)


def poly_mmd(
    f_real: Array, f_fake: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    """MMD under the polynomial kernel (reference ``kid.py:44-51``)."""
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)
    return maximum_mean_discrepancy(k_11, k_12, k_22)


class KernelInceptionDistance(Metric):
    """KID = MMD² over feature subsets (reference ``kid.py:54-260``)."""

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    real_features: List[Array]
    fake_features: List[Array]

    def __init__(
        self,
        feature: Union[str, int, Callable[[Array], Array]] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        normalize: bool = False,
        num_features: Optional[int] = None,
        allow_random_features: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `KernelInceptionDistance` will save all extracted features in buffer."
            " For large datasets this may lead to large memory footprint.",
            UserWarning,
        )
        self.inception, _ = resolve_feature_extractor(
            feature, num_features, allow_random_features=allow_random_features
        )
        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize

        self.add_state("real_features", [], dist_reduce_fx=None)
        self.add_state("fake_features", [], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        """Extract and buffer features (reference ``kid.py:222-233``)."""
        imgs = (imgs * 255).astype(jnp.uint8) if self.normalize else imgs
        features = self.inception(imgs)
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        """Mean/std of subset MMDs, vmapped over subsets (reference ``kid.py:235-260``)."""
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)

        n_samples_real = real_features.shape[0]
        if n_samples_real < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        n_samples_fake = fake_features.shape[0]
        if n_samples_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")

        # subset indices drawn on host (epoch-end), scored in one vmapped device batch
        real_idx = np.stack(
            [np.random.permutation(n_samples_real)[: self.subset_size] for _ in range(self.subsets)]
        )
        fake_idx = np.stack(
            [np.random.permutation(n_samples_fake)[: self.subset_size] for _ in range(self.subsets)]
        )

        def _one(ri: Array, fi: Array) -> Array:
            return poly_mmd(real_features[ri], fake_features[fi], self.degree, self.gamma, self.coef)

        kid_scores = jax.vmap(_one)(jnp.asarray(real_idx), jnp.asarray(fake_idx))
        return kid_scores.mean(), kid_scores.std(ddof=0)

    def reset(self) -> None:
        """Reset, optionally keeping the real features (reference ``kid.py:262-270``)."""
        if not self.reset_real_features:
            value = self.real_features
            super().reset()
            self.real_features = value
        else:
            super().reset()

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        val = val if val is not None else self.compute()[0]
        return self._plot(val, ax)
