"""Modular SSIM / MS-SSIM (reference ``src/torchmetrics/image/ssim.py``).

Reduction-dependent state layout (reference ``ssim.py:106-115``): scalar sums for
``elementwise_mean``/``sum`` (one psum at sync), cat lists for ``none``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.ssim import (
    _multiscale_ssim_update,
    _ssim_check_inputs,
    _ssim_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class StructuralSimilarityIndexMeasure(Metric):
    """SSIM (reference ``ssim.py:33-219``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.image.ssim import StructuralSimilarityIndexMeasure
        >>> metric = StructuralSimilarityIndexMeasure()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.9591
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        gaussian_kernel: bool = True,
        sigma: Union[float, Sequence[float]] = 1.5,
        kernel_size: Union[int, Sequence[int]] = 11,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        return_full_image: bool = False,
        return_contrast_sensitivity: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")

        if reduction in ("elementwise_mean", "sum"):
            self.add_state("similarity", jnp.asarray(0.0), dist_reduce_fx="sum")
        else:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

        if return_contrast_sensitivity or return_full_image:
            self.add_state("image_return", [], dist_reduce_fx="cat")

        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.return_full_image = return_full_image
        self.return_contrast_sensitivity = return_contrast_sensitivity

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-image similarities (reference ``ssim.py:127-155``)."""
        preds, target = _ssim_check_inputs(preds, target)
        similarity_pack = _ssim_update(
            preds,
            target,
            self.gaussian_kernel,
            self.sigma,
            self.kernel_size,
            self.data_range,
            self.k1,
            self.k2,
            self.return_full_image,
            self.return_contrast_sensitivity,
        )
        if isinstance(similarity_pack, tuple):
            similarity, image = similarity_pack
            self.image_return.append(image)
        else:
            similarity = similarity_pack

        if self.reduction in ("elementwise_mean", "sum"):
            self.similarity = self.similarity + similarity.sum()
            self.total = self.total + preds.shape[0]
        else:
            self.similarity.append(similarity)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Final (optionally reduced) SSIM (reference ``ssim.py:157-173``)."""
        if self.reduction == "elementwise_mean":
            similarity = self.similarity / self.total
        elif self.reduction == "sum":
            similarity = self.similarity
        else:
            similarity = dim_zero_cat(self.similarity)

        if self.return_contrast_sensitivity or self.return_full_image:
            image_return = dim_zero_cat(self.image_return)
            return similarity, image_return
        return similarity

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class MultiScaleStructuralSimilarityIndexMeasure(Metric):
    """MS-SSIM (reference ``ssim.py:222-419``).
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        gaussian_kernel: bool = True,
        kernel_size: Union[int, Sequence[int]] = 11,
        sigma: Union[float, Sequence[float]] = 1.5,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
        normalize: Optional[str] = "relu",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")

        if reduction in ("elementwise_mean", "sum"):
            self.add_state("similarity", jnp.asarray(0.0), dist_reduce_fx="sum")
        else:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

        if not (isinstance(kernel_size, (Sequence, int))):
            raise ValueError(
                f"Argument `kernel_size` expected to be an sequence or an int, or a single int. Got {kernel_size}"
            )
        if isinstance(kernel_size, Sequence) and (
            len(kernel_size) not in (2, 3) or not all(isinstance(ks, int) for ks in kernel_size)
        ):
            raise ValueError(
                "Argument `kernel_size` expected to be an sequence of size 2 or 3 where each element is an int,"
                f" or a single int. Got {kernel_size}"
            )

        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
            raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
        self.betas = betas
        if normalize and normalize not in ("relu", "simple"):
            raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
        self.normalize = normalize

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-image MS-SSIM values (reference ``ssim.py:341-362``)."""
        preds, target = _ssim_check_inputs(preds, target)
        similarity = _multiscale_ssim_update(
            preds,
            target,
            self.gaussian_kernel,
            self.sigma,
            self.kernel_size,
            self.data_range,
            self.k1,
            self.k2,
            self.betas,
            self.normalize,
        )
        if self.reduction in ("elementwise_mean", "sum"):
            self.similarity = self.similarity + similarity.sum()
            self.total = self.total + preds.shape[0]
        else:
            self.similarity.append(similarity)

    def compute(self) -> Array:
        """Final (optionally reduced) MS-SSIM (reference ``ssim.py:364-374``)."""
        if self.reduction == "elementwise_mean":
            return self.similarity / self.total
        if self.reduction == "sum":
            return self.similarity
        return dim_zero_cat(self.similarity)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
