"""Frechet Inception Distance (reference ``src/torchmetrics/image/fid.py``).

TPU-first design:
- Streaming sum / Σxxᵀ / count states (fixed shapes, one psum each at sync) — same
  layout as the reference (``fid.py:315-321``).
- The update is **row-additive and branchless**: the real/fake flag rides as a 0-d
  input (``jnp.where`` select, no Python branch), so the engine compiles ONE donated
  executable covering both streams and the ragged tail rides the power-of-two shape
  buckets like any counter metric (``_engine_row_additive``). The extractor must be
  row-independent (per-image features, no cross-batch normalisation) — that is what
  the row-additive declaration asserts.
- ``trace(sqrtm(Σ₁Σ₂))`` via symmetric eigendecomposition: for PSD Σ₁, Σ₂ the
  eigvals of Σ₁Σ₂ equal those of the *symmetric* Σ₁^½ Σ₂ Σ₁^½, so two ``eigh`` calls
  replace the reference's general-matrix ``torch.linalg.eigvals`` (``fid.py:160-179``).
  The Fréchet compute runs **in-graph** by default (``jnp.linalg.eigvalsh`` — one XLA
  graph, no host readback, STRICT-guard clean); the legacy host-numpy path is retained
  behind ``TORCHMETRICS_TPU_FID_HOST_EIGH`` as a counted, boundary-sanctioned fallback
  for deployments where a device eig kernel degrades the accelerator stream (the
  tunneled-TPU pathology: one eigh dropped every later dispatch ~0.03 ms → ~104 ms).
- The ``(d, d)`` covariance-sum states declare ``row_sharded``: on an active state
  mesh (``parallel/sharding.py``) a 2048-dim (or larger) feature covariance is born
  partitioned over the mesh rows — ``state_footprint()`` proves ~1/mesh bytes per
  device — and the SPMD update scatters each batch's Σxxᵀ contribution shard-locally.
- Accumulation in f64 like the reference; on TPU (no native f64) XLA emulates — the
  compute runs once per epoch so this is off the hot path.
"""

from __future__ import annotations

import os
import weakref
from typing import Any, Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.engine.stats import EngineStats
from torchmetrics_tpu.image._extractor import resolve_feature_extractor
from torchmetrics_tpu.metric import Metric

Array = jax.Array

# f64 under x64 (host/test runs, matching the reference's .double()); f32 on TPU where
# native f64 is absent — resolved via result_type so no dtype-truncation warnings fire.
_F64 = jnp.result_type(jnp.float32, jnp.float64)

_HOST_EIGH_ENV = "TORCHMETRICS_TPU_FID_HOST_EIGH"

# module-level stats block: heavy-workload host fallbacks are a process-wide
# fact, not a per-engine property — one EngineStats joins the weak registry so
# engine_report()/telemetry aggregate `fid_host_eighs` like any other counter
_STATS = EngineStats("fid")

# extractor output dtypes observed per live metric instance. The traced update
# cannot write `self.orig_dtype` (any non-state attribute write aborts
# compilation), but a tracer's dtype is STATIC metadata — recording it here is
# a trace-safe, idempotent side effect, so engine-only streams still report the
# extractor's dtype from compute(). id-keyed with a finalizer (Metric.__hash__
# is state-dependent, so WeakKeyDictionary is off the table).
_ORIG_DTYPES: Dict[int, Any] = {}


def _note_orig_dtype(metric: "FrechetInceptionDistance", dtype: Any) -> None:
    key = id(metric)
    if key not in _ORIG_DTYPES:
        _ORIG_DTYPES[key] = dtype
        weakref.finalize(metric, _ORIG_DTYPES.pop, key, None)
    else:
        _ORIG_DTYPES[key] = dtype


def fid_host_eigh() -> bool:
    """Whether the Fréchet compute takes the retained host-eigh fallback.

    ``TORCHMETRICS_TPU_FID_HOST_EIGH=1|on`` routes the epoch-end eigendecompositions
    to host LAPACK (the pre-r17 behavior — keeps eig kernels OFF the accelerator
    stream where a tunneled-TPU dispatch pathology makes them toxic); unset/``0``/
    ``off`` keeps the compute in-graph. Unrecognized values fail loud (the PR-7 env
    contract). Each host compute is counted (``fid_host_eighs``) and recorded as a
    ``heavy.fallback`` event, and its readbacks ride the sanctioned
    ``fid-host-eigh`` transfer boundary.
    """
    raw = os.environ.get(_HOST_EIGH_ENV, "").strip().lower()
    if raw in ("", "0", "off"):
        return False
    if raw in ("1", "on"):
        return True
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    raise TorchMetricsUserError(
        f"{_HOST_EIGH_ENV} must be unset/'0'/'off' or '1'/'on' (got {raw!r})"
    )


def _sqrtm_psd(mat):
    """Matrix square root of a symmetric PSD matrix via host eigh (numpy)."""
    w, v = np.linalg.eigh(mat)
    w = np.clip(w, 0.0, None)
    return (v * np.sqrt(w)) @ v.T


def _compute_fid_host(mu1, sigma1, mu2, sigma2) -> Array:
    """The retained host-numpy Fréchet path (``TORCHMETRICS_TPU_FID_HOST_EIGH``).

    One-shot (d, d) LAPACK calls at epoch end, kept for deployments where device
    eig kernels must stay off the accelerator stream. Counted + sanctioned: the
    readbacks ride the registered ``fid-host-eigh`` boundary so a STRICT guard
    stays clean by declaration rather than suppression.
    """
    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    if jax.core.trace_state_clean():
        # an epoch-engine trace attempt reaches here with tracers and aborts at
        # the first readback — only the eager evaluation that runs counts
        _STATS.fid_host_eighs += 1
        _diag.record(
            "heavy.fallback", "FrechetInceptionDistance",
            label="fid-host-eigh", reason="knob",
        )
    with transfer_allowed("fid-host-eigh"):
        mu1, mu2 = np.asarray(mu1), np.asarray(mu2)
        sigma1, sigma2 = np.asarray(sigma1), np.asarray(sigma2)
    a = ((mu1 - mu2) ** 2).sum(axis=-1)
    b = np.trace(sigma1) + np.trace(sigma2)
    s1_half = _sqrtm_psd(sigma1)
    m = s1_half @ sigma2 @ s1_half
    eig = np.linalg.eigvalsh(m)
    c = np.sqrt(np.clip(eig, 0.0, None)).sum(axis=-1)
    return jnp.asarray(a + b - 2 * c)


def _compute_fid_jnp(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array) -> Array:
    """d² = ‖μ₁−μ₂‖² + Tr(Σ₁+Σ₂−2√(Σ₁Σ₂)) in one XLA graph (reference ``fid.py:160-179``)."""
    a = ((mu1 - mu2) ** 2).sum(axis=-1)
    b = jnp.trace(sigma1) + jnp.trace(sigma2)
    w1, v1 = jnp.linalg.eigh(sigma1)
    s1_half = (v1 * jnp.sqrt(jnp.clip(w1, 0.0, None))) @ v1.T
    m = s1_half @ sigma2 @ s1_half
    eig = jnp.linalg.eigvalsh(m)
    c = jnp.sqrt(jnp.clip(eig, 0.0, None)).sum(axis=-1)
    return a + b - 2 * c


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array) -> Array:
    """Fréchet distance — in-graph by default, host-eigh behind the knob."""
    if fid_host_eigh():
        return _compute_fid_host(mu1, sigma1, mu2, sigma2)
    return _compute_fid_jnp(mu1, sigma1, mu2, sigma2)


class FrechetInceptionDistance(Metric):
    """FID with streaming covariance states (reference ``fid.py:182-365``).

    Args:
        feature: callable ``imgs -> (N, d)`` feature extractor (see
            :mod:`torchmetrics_tpu.image._extractor`).
        reset_real_features: whether ``reset`` clears the real-distribution states.
        normalize: if True, float [0,1] inputs are scaled to [0,255] uint8 first.
        num_features: feature dim; probed from a dummy forward when ``None``.

    Engine notes: pass ``real`` as a 0-d jax array (``jnp.asarray(True)``) to ride
    the compiled/bucketed/scan hot path — a Python bool is a non-array input and
    runs the exact same branchless body eagerly. The covariance-sum states declare
    ``row_sharded``: with an active state mesh they are born partitioned (~1/mesh
    bytes per device, in-graph psum sync).
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    # the update is additive over batch rows (Σ over per-image features) and every
    # state folds with "sum" — the bucketing pad-subtract identity holds, PROVIDED
    # the extractor maps each image independently (documented requirement)
    _engine_row_additive: bool = True
    # SPMD placement (parallel/sharding.py): the (d, d) covariance sums partition
    # their leading dim over the state mesh; no active mesh = replicated, free
    _engine_shard_rules = {
        "real_features_cov_sum": "row_sharded",
        "fake_features_cov_sum": "row_sharded",
    }

    def __init__(
        self,
        feature: Union[int, str, Callable[[Array], Array]] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        num_features: Optional[int] = None,
        allow_random_features: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, num_features = resolve_feature_extractor(
            feature, num_features, allow_random_features=allow_random_features
        )
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self.num_features = num_features

        mx = (num_features, num_features)
        self.add_state("real_features_sum", jnp.zeros(num_features, dtype=_F64), dist_reduce_fx="sum")
        self.add_state("real_features_cov_sum", jnp.zeros(mx, dtype=_F64), dist_reduce_fx="sum")
        self.add_state("real_features_num_samples", jnp.asarray(0), dist_reduce_fx="sum")
        self.add_state("fake_features_sum", jnp.zeros(num_features, dtype=_F64), dist_reduce_fx="sum")
        self.add_state("fake_features_cov_sum", jnp.zeros(mx, dtype=_F64), dist_reduce_fx="sum")
        self.add_state("fake_features_num_samples", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, imgs: Array, real: Union[bool, Array]) -> None:
        """Extract features and fold them into the streaming moments (reference ``fid.py:323-339``).

        Branchless: both real and fake states update every step, masked by the
        ``real`` flag — so a 0-d array flag traces into ONE compiled executable
        serving both streams (a Python bool runs the identical arithmetic eagerly).
        """
        imgs = (imgs * 255).astype(jnp.uint8) if self.normalize else imgs
        features = self.inception(imgs)
        # the dtype is static even on a tracer; the external registry makes it
        # observable from compute() on engine-only streams, while the pickle-
        # visible attribute mirror is written on the eager path only (a traced
        # non-state attribute write would abort compilation)
        _note_orig_dtype(self, features.dtype)
        if not isinstance(features, jax.core.Tracer) and getattr(self, "orig_dtype", None) != features.dtype:
            self.orig_dtype = features.dtype
        features = features.astype(_F64)
        if features.ndim == 1:
            features = features[None, :]
        n = features.shape[0]
        fsum = features.sum(axis=0)
        fcov = features.T @ features
        r = jnp.asarray(real)
        cnt_dtype = self.real_features_num_samples.dtype
        # where-SELECTS, not arithmetic masking: `0 * inf = NaN` would let one
        # non-finite batch poison the OTHER stream's states — the unselected
        # branch of a select cannot contaminate the selected lanes, so the two
        # streams stay isolated exactly like the old if/else. The pad-subtract
        # identity still holds per branch (the unit run selects the same side).
        self.real_features_sum = jnp.where(r, self.real_features_sum + fsum, self.real_features_sum)
        self.real_features_cov_sum = jnp.where(r, self.real_features_cov_sum + fcov, self.real_features_cov_sum)
        self.real_features_num_samples = self.real_features_num_samples + jnp.where(r, n, 0).astype(cnt_dtype)
        self.fake_features_sum = jnp.where(r, self.fake_features_sum, self.fake_features_sum + fsum)
        self.fake_features_cov_sum = jnp.where(r, self.fake_features_cov_sum, self.fake_features_cov_sum + fcov)
        self.fake_features_num_samples = self.fake_features_num_samples + jnp.where(r, 0, n).astype(cnt_dtype)

    def _epoch_sync_for_compute(self):
        """Decline the fused sync→compute chain — it returns a value without
        re-entering ``_engine_compute``, which would skip the <2-sample guard
        on multi-process runs. The packed sync still rides ``sync_context``;
        the guard then reads the SYNCED counts and the cached compute
        executable serves the value (two epoch-end dispatches instead of one —
        noise next to the Fréchet eigendecompositions)."""
        return None

    def _engine_compute(self, compute, args, kwargs):
        """Host-side pre-dispatch hook covering cached AND eager compute.

        The cached-compute executable never re-enters the Python body, so the
        reference's <2-sample guard must run here — one sanctioned scalar read
        per compute call, at the epoch boundary, cached-path included (a reset
        metric raises exactly like the pre-engine path instead of dispatching
        a graph that folds 0/0 into NaN). The same host moment mirrors the
        engine-observed extractor dtype onto the pickle/clone-visible
        ``orig_dtype`` attribute (the traced update cannot write it).
        """
        dtype = _ORIG_DTYPES.get(id(self))
        if dtype is not None and self.__dict__.get("orig_dtype") is None:
            self.orig_dtype = dtype
        from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

        with transfer_allowed("fid-sample-guard"):
            n_real = int(self.real_features_num_samples)
            n_fake = int(self.fake_features_num_samples)
        if n_real < 2 or n_fake < 2:
            raise RuntimeError(
                "More than one sample is required for both the real and fake distributed to compute FID"
            )
        if fid_host_eigh():
            # the retained host path must bypass the CACHED in-graph executable:
            # the knob can flip mid-process (the documented tunneled-TPU
            # remediation), and a cached graph would silently ignore it
            return compute(*args, **kwargs)
        return super()._engine_compute(compute, args, kwargs)

    def __getstate__(self) -> Dict[str, Any]:
        """Mirror the engine-observed extractor dtype into the pickled state.

        On an engine-only stream the traced update cannot write ``orig_dtype``
        and the id-keyed registry does not follow a pickle/clone — without this,
        a copy taken after updates but before the first compute would return the
        accumulation dtype instead of the extractor's.
        """
        state = super().__getstate__()
        if state.get("orig_dtype") is None:
            dtype = _ORIG_DTYPES.get(id(self))
            if dtype is not None:
                state["orig_dtype"] = dtype
        return state

    def compute(self) -> Array:
        """FID between the two accumulated gaussians (reference ``fid.py:341-352``).

        Fully traceable when the host-eigh knob is off: the epoch engine caches
        it as ONE ledger-verified executable and the STRICT transfer guard
        holds (the <2-sample guard runs in the host-side ``_engine_compute``
        hook, never in this body).
        """
        n_real = self.real_features_num_samples
        n_fake = self.fake_features_num_samples
        mean_real = (self.real_features_sum / n_real)[None, :]
        mean_fake = (self.fake_features_sum / n_fake)[None, :]

        cov_real_num = self.real_features_cov_sum - n_real * (mean_real.T @ mean_real)
        cov_real = cov_real_num / (n_real - 1)
        cov_fake_num = self.fake_features_cov_sum - n_fake * (mean_fake.T @ mean_fake)
        cov_fake = cov_fake_num / (n_fake - 1)
        out = _compute_fid(mean_real.squeeze(0), cov_real, mean_fake.squeeze(0), cov_fake)
        orig = getattr(self, "orig_dtype", None) or _ORIG_DTYPES.get(id(self))
        return out.astype(orig if orig is not None else out.dtype)

    def reset(self) -> None:
        """Reset, optionally keeping the real-distribution statistics (reference ``fid.py:354-365``)."""
        if not self.reset_real_features:
            real_features_sum = self.real_features_sum
            real_features_cov_sum = self.real_features_cov_sum
            real_features_num_samples = self.real_features_num_samples
            super().reset()
            self.real_features_sum = real_features_sum
            self.real_features_cov_sum = real_features_cov_sum
            self.real_features_num_samples = real_features_num_samples
        else:
            super().reset()

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
