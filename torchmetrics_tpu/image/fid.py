"""Frechet Inception Distance (reference ``src/torchmetrics/image/fid.py``).

TPU-first design:
- Streaming sum / Σxxᵀ / count states (fixed shapes, one psum each at sync) — same
  layout as the reference (``fid.py:315-321``).
- ``trace(sqrtm(Σ₁Σ₂))`` via symmetric eigendecomposition: for PSD Σ₁, Σ₂ the
  eigvals of Σ₁Σ₂ equal those of the *symmetric* Σ₁^½ Σ₂ Σ₁^½, so two ``eigh`` calls
  replace the reference's general-matrix ``torch.linalg.eigvals`` (``fid.py:160-179``)
  — ``eigh`` lowers to XLA on TPU, general ``eigvals`` does not.
- Accumulation in f64 like the reference; on TPU (no native f64) XLA emulates — the
  compute runs once per epoch so this is off the hot path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.image._extractor import resolve_feature_extractor
from torchmetrics_tpu.metric import Metric

Array = jax.Array

# f64 under x64 (host/test runs, matching the reference's .double()); f32 on TPU where
# native f64 is absent — resolved via result_type so no dtype-truncation warnings fire.
_F64 = jnp.result_type(jnp.float32, jnp.float64)


def _sqrtm_psd(mat):
    """Matrix square root of a symmetric PSD matrix via host eigh (numpy)."""
    w, v = np.linalg.eigh(mat)
    w = np.clip(w, 0.0, None)
    return (v * np.sqrt(w)) @ v.T


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array) -> Array:
    """d² = ‖μ₁−μ₂‖² + Tr(Σ₁+Σ₂−2√(Σ₁Σ₂)) (reference ``fid.py:160-179``).

    Runs on host numpy: the eigendecompositions are one-shot (d,d) LAPACK calls at
    epoch end, and device eig kernels must be kept OFF the accelerator stream — on
    the tunneled TPU a single eigh permanently degrades every subsequent dispatch
    (~0.03 ms → ~104 ms), poisoning the training hot loop that follows ``compute``.
    """
    mu1, mu2 = np.asarray(mu1), np.asarray(mu2)
    sigma1, sigma2 = np.asarray(sigma1), np.asarray(sigma2)
    a = ((mu1 - mu2) ** 2).sum(axis=-1)
    b = np.trace(sigma1) + np.trace(sigma2)
    s1_half = _sqrtm_psd(sigma1)
    m = s1_half @ sigma2 @ s1_half
    eig = np.linalg.eigvalsh(m)
    c = np.sqrt(np.clip(eig, 0.0, None)).sum(axis=-1)
    return jnp.asarray(a + b - 2 * c)


class FrechetInceptionDistance(Metric):
    """FID with streaming covariance states (reference ``fid.py:182-365``).

    Args:
        feature: callable ``imgs -> (N, d)`` feature extractor (see
            :mod:`torchmetrics_tpu.image._extractor`).
        reset_real_features: whether ``reset`` clears the real-distribution states.
        normalize: if True, float [0,1] inputs are scaled to [0,255] uint8 first.
        num_features: feature dim; probed from a dummy forward when ``None``.
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        feature: Union[int, str, Callable[[Array], Array]] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        num_features: Optional[int] = None,
        allow_random_features: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, num_features = resolve_feature_extractor(
            feature, num_features, allow_random_features=allow_random_features
        )
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self.num_features = num_features

        mx = (num_features, num_features)
        self.add_state("real_features_sum", jnp.zeros(num_features, dtype=_F64), dist_reduce_fx="sum")
        self.add_state("real_features_cov_sum", jnp.zeros(mx, dtype=_F64), dist_reduce_fx="sum")
        self.add_state("real_features_num_samples", jnp.asarray(0), dist_reduce_fx="sum")
        self.add_state("fake_features_sum", jnp.zeros(num_features, dtype=_F64), dist_reduce_fx="sum")
        self.add_state("fake_features_cov_sum", jnp.zeros(mx, dtype=_F64), dist_reduce_fx="sum")
        self.add_state("fake_features_num_samples", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, imgs: Array, real: bool) -> None:
        """Extract features and fold them into the streaming moments (reference ``fid.py:323-339``)."""
        imgs = (imgs * 255).astype(jnp.uint8) if self.normalize else imgs
        features = self.inception(imgs)
        self.orig_dtype = features.dtype
        features = features.astype(_F64)
        if features.ndim == 1:
            features = features[None, :]
        if real:
            self.real_features_sum = self.real_features_sum + features.sum(axis=0)
            self.real_features_cov_sum = self.real_features_cov_sum + features.T @ features
            self.real_features_num_samples = self.real_features_num_samples + imgs.shape[0]
        else:
            self.fake_features_sum = self.fake_features_sum + features.sum(axis=0)
            self.fake_features_cov_sum = self.fake_features_cov_sum + features.T @ features
            self.fake_features_num_samples = self.fake_features_num_samples + imgs.shape[0]

    def compute(self) -> Array:
        """FID between the two accumulated gaussians (reference ``fid.py:341-352``)."""
        if int(self.real_features_num_samples) < 2 or int(self.fake_features_num_samples) < 2:
            raise RuntimeError("More than one sample is required for both the real and fake distributed to compute FID")
        mean_real = (self.real_features_sum / self.real_features_num_samples)[None, :]
        mean_fake = (self.fake_features_sum / self.fake_features_num_samples)[None, :]

        cov_real_num = self.real_features_cov_sum - self.real_features_num_samples * (mean_real.T @ mean_real)
        cov_real = cov_real_num / (self.real_features_num_samples - 1)
        cov_fake_num = self.fake_features_cov_sum - self.fake_features_num_samples * (mean_fake.T @ mean_fake)
        cov_fake = cov_fake_num / (self.fake_features_num_samples - 1)
        out = _compute_fid(mean_real.squeeze(0), cov_real, mean_fake.squeeze(0), cov_fake)
        return out.astype(getattr(self, "orig_dtype", out.dtype))

    def reset(self) -> None:
        """Reset, optionally keeping the real-distribution statistics (reference ``fid.py:354-365``)."""
        if not self.reset_real_features:
            real_features_sum = self.real_features_sum
            real_features_cov_sum = self.real_features_cov_sum
            real_features_num_samples = self.real_features_num_samples
            super().reset()
            self.real_features_sum = real_features_sum
            self.real_features_cov_sum = real_features_cov_sum
            self.real_features_num_samples = real_features_num_samples
        else:
            super().reset()

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
