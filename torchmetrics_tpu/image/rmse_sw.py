"""Modular windowed RMSE (reference ``src/torchmetrics/image/rmse_sw.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.rmse_sw import _rmse_sw_compute, _rmse_sw_update
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class RootMeanSquaredErrorUsingSlidingWindow(Metric):
    """Windowed RMSE (reference ``rmse_sw.py:24-99``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.image.rmse_sw import RootMeanSquaredErrorUsingSlidingWindow
        >>> metric = RootMeanSquaredErrorUsingSlidingWindow()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.0763
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError("Argument `window_size` is expected to be a positive integer.")
        self.window_size = window_size
        self.add_state("rmse_val_sum", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_images", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-batch windowed RMSE sums."""
        rmse_val_sum, _, total_images = _rmse_sw_update(
            preds, target, self.window_size, rmse_val_sum=None, rmse_map=None, total_images=None
        )
        self.rmse_val_sum = self.rmse_val_sum + rmse_val_sum
        self.total_images = self.total_images + total_images

    def compute(self) -> Optional[Array]:
        """Mean windowed RMSE."""
        rmse, _ = _rmse_sw_compute(self.rmse_val_sum, rmse_map=None, total_images=self.total_images)
        return rmse

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
