"""Modular Spectral Distortion Index (reference ``src/torchmetrics/image/d_lambda.py``)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from torchmetrics_tpu.functional.image.d_lambda import (
    _spectral_distortion_index_compute,
    _spectral_distortion_index_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class SpectralDistortionIndex(Metric):
    """D_lambda (reference ``d_lambda.py:26-123``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.image.d_lambda import SpectralDistortionIndex
        >>> metric = SpectralDistortionIndex()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.0002
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds: List[Array]
    target: List[Array]

    def __init__(self, p: int = 1, reduction: str = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(p, int) or p <= 0:
            raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
        self.p = p
        allowed_reductions = ("elementwise_mean", "sum", "none")
        if reduction not in allowed_reductions:
            raise ValueError(f"Expected argument `reduction` be one of {allowed_reductions} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Buffer one batch of image pairs."""
        preds, target = _spectral_distortion_index_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """D_lambda over all buffered images."""
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spectral_distortion_index_compute(preds, target, self.p, self.reduction)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
