"""Rank-zero-gated printing helpers.

Capability parity: reference ``src/torchmetrics/utilities/prints.py:22-71``. On TPU the
process index comes from ``jax.process_index()`` (falling back to the ``LOCAL_RANK`` env
var so launcher scripts behave identically), not ``torch.distributed``.
"""

from __future__ import annotations

import os
import warnings
from functools import partial, wraps
from typing import Any, Callable


def _get_rank() -> int:
    rank = os.environ.get("LOCAL_RANK", None)
    if rank is not None:
        return int(rank)
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on global rank zero (reference ``prints.py:22-38``)."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _get_rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def rank_zero_warn(message: str, category: type = UserWarning, stacklevel: int = 5, **kwargs: Any) -> None:
    warnings.warn(message, category=category, stacklevel=stacklevel, **kwargs)


@rank_zero_only
def rank_zero_info(message: str, **kwargs: Any) -> None:
    print(message, **kwargs)


@rank_zero_only
def rank_zero_debug(message: str, **kwargs: Any) -> None:
    if os.environ.get("TM_TPU_DEBUG"):
        print(message, **kwargs)


def _deprecated_root_import_class(name: str, domain: str) -> None:
    """Warn that root import of a domain metric class is deprecated (ref ``prints.py:59-65``)."""
    rank_zero_warn(
        f"`torchmetrics_tpu.{name}` was deprecated and will be removed in 2.0."
        f" Import `torchmetrics_tpu.{domain}.{name}` instead.",
        DeprecationWarning,
    )


def _deprecated_root_import_func(name: str, domain: str) -> None:
    """Warn that root import of a domain functional is deprecated (ref ``prints.py:66-71``)."""
    rank_zero_warn(
        f"`torchmetrics_tpu.functional.{name}` was deprecated and will be removed in 2.0."
        f" Import `torchmetrics_tpu.functional.{domain}.{name}` instead.",
        DeprecationWarning,
    )
