"""Core utilities (reference ``src/torchmetrics/utilities/__init__.py``)."""

from torchmetrics_tpu.utilities.checks import check_forward_full_state_property
from torchmetrics_tpu.utilities.data import (
    apply_to_collection,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    select_topk,
    to_categorical,
    to_onehot,
)
from torchmetrics_tpu.utilities.distributed import class_reduce, gather_all_tensors, reduce
from torchmetrics_tpu.utilities.prints import rank_zero_debug, rank_zero_info, rank_zero_warn

__all__ = [
    "apply_to_collection",
    "check_forward_full_state_property",
    "class_reduce",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "gather_all_tensors",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
    "reduce",
    "select_topk",
    "to_categorical",
    "to_onehot",
]
