"""Checkpoint/resume for metric states via orbax (SURVEY §5.4).

The reference persists metric states through ``state_dict``/``load_state_dict`` inside
a torch checkpoint (``src/torchmetrics/metric.py:768-816``). Here states are jax
pytrees, so they ride orbax — the TPU-ecosystem checkpointer (async, sharding-aware) —
with a numpy ``.npz`` fallback when orbax is unavailable. The update count is saved
alongside the states so weighted merges (``merge_state``) stay correct after resume,
matching ``Metric.load_state_dict``'s contract.

Works for single metrics and ``MetricCollection``s (any object exposing
``state_dict``/``load_state_dict``).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

try:
    import orbax.checkpoint as ocp

    _ORBAX_AVAILABLE = True
except Exception:  # pragma: no cover
    _ORBAX_AVAILABLE = False


def _to_saveable(state: Dict[str, Any]) -> Dict[str, Any]:
    """state_dict values -> arrays (list states become stacked arrays + length tag)."""
    out: Dict[str, Any] = {}
    for key, value in state.items():
        if isinstance(value, list):
            out[f"{key}.__list__"] = np.asarray(len(value))
            for i, item in enumerate(value):
                out[f"{key}.{i}"] = np.asarray(item)
        else:
            out[key] = np.asarray(value)
    return out


def _from_saveable(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    lists = {k[: -len(".__list__")]: int(v) for k, v in flat.items() if k.endswith(".__list__")}
    for key, length in lists.items():
        out[key] = [jnp.asarray(flat[f"{key}.{i}"]) for i in range(length)]
    for key, value in flat.items():
        if key.endswith(".__list__"):
            continue
        base = key.rsplit(".", 1)[0]
        if base in lists and key[len(base) :].lstrip(".").isdigit():
            continue
        out[key] = jnp.asarray(value)
    return out


def save_metric_state(metric: Any, path: str) -> None:
    """Persist ALL of a metric's (or collection's) states + update counts.

    Unlike ``state_dict`` (which honours per-state ``persistent`` flags, same rule as
    the reference), a resume checkpoint needs every state — so persistence is forced
    on only for the duration of the snapshot and the flags are restored afterwards.
    Uses orbax when available (``path`` becomes a checkpoint directory), else a
    ``.npz`` file.
    """
    saved_flags = _snapshot_persistence(metric)
    try:
        metric.persistent(True)
        flat = _to_saveable(metric.state_dict())
    finally:
        _restore_persistence(metric, saved_flags)
    if _ORBAX_AVAILABLE:
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), flat, force=True)
    else:
        np.savez(path if path.endswith(".npz") else path + ".npz", **flat)


def restore_metric_state(metric: Any, path: str) -> Any:
    """Restore states saved by :func:`save_metric_state` into ``metric`` (in place)."""
    if _ORBAX_AVAILABLE and os.path.isdir(path):
        ckptr = ocp.PyTreeCheckpointer()
        flat = ckptr.restore(os.path.abspath(path))
    else:
        npz = np.load(path if path.endswith(".npz") else path + ".npz")
        flat = dict(npz)
    metric.load_state_dict(_from_saveable(flat))
    for m in _metrics_of(metric):  # drop any cached compute() value — state just changed
        m._computed = None
    return metric


def _metrics_of(metric: Any):
    """Leaf Metric objects of a metric or collection."""
    from torchmetrics_tpu.collections import MetricCollection  # local import avoids a cycle

    if isinstance(metric, MetricCollection):
        # copy_state=False: a persistence snapshot must see the live objects, not
        # compute-group state copies
        return metric.values(copy_state=False)
    return [metric]


def _snapshot_persistence(metric: Any) -> list:
    return [dict(m._persistent) for m in _metrics_of(metric)]


def _restore_persistence(metric: Any, flags: list) -> None:
    for m, saved in zip(_metrics_of(metric), flags):
        m._persistent.update(saved)
