"""String enums used across the metric packages.

Capability parity: reference ``src/torchmetrics/utilities/enums.py:20-148``.
Implemented on plain ``str``-``Enum`` (no lightning_utilities dependency): values
compare case-insensitively against strings and ``from_str`` resolves user input.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """Case-insensitive string enum base (reference ``enums.py:20-52``)."""

    @classmethod
    def _name(cls) -> str:
        return "Task"

    @classmethod
    def from_str(cls, value: str, source: str = "key") -> "EnumStr":
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError:
            pass
        try:
            return cls(value.lower())
        except ValueError:
            raise ValueError(
                f"Invalid {cls._name()}: expected one of {[e.value for e in cls]}, but got {value}."
            ) from None

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Enum):
            other = other.value
        return self.value.lower() == str(other).lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """Type-category of classification inputs (reference ``enums.py:55-70``)."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"

    @classmethod
    def _name(cls) -> str:
        return "Data type"


class AverageMethod(EnumStr):
    """Reduction over classes (reference ``enums.py:73-94``)."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"

    @classmethod
    def _name(cls) -> str:
        return "Average method"


class MDMCAverageMethod(EnumStr):
    """Multi-dim multi-class reduction (reference ``enums.py:97-104``)."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """Task router values (reference ``enums.py:107-125``)."""

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoBinary(EnumStr):
    """Reference ``enums.py:128-137``."""

    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoMultilabel(EnumStr):
    """Reference ``enums.py:140-148``."""

    BINARY = "binary"
    MULTICLASS = "multiclass"


def _str_or_none(value: Optional[str]) -> Optional[str]:
    return None if value is None else str(value)
