"""Root-alias deprecation shims.

The reference keeps domain metrics importable from the package root but deprecated:
per-domain ``_deprecated.py`` modules define ``_X(X)`` subclasses that warn on
construction via ``_deprecated_root_import_class``, and the root ``__init__`` exports
those under the plain names (reference ``src/torchmetrics/__init__.py`` +
``image/_deprecated.py`` etc.). ``root_alias`` builds such a subclass; importing from
``torchmetrics_tpu.<domain>`` stays warning-free.
"""

from __future__ import annotations

from typing import Any, Type

from torchmetrics_tpu.utilities.prints import _deprecated_root_import_class


def root_alias(cls: Type, domain: str) -> Type:
    """Subclass ``cls`` so that construction warns about the deprecated root import."""

    class _RootAlias(cls):  # type: ignore[misc,valid-type]
        def __init__(self, *args: Any, **kwargs: Any) -> None:
            _deprecated_root_import_class(cls.__name__, domain)
            super().__init__(*args, **kwargs)

    _RootAlias.__name__ = f"_{cls.__name__}"
    _RootAlias.__qualname__ = f"_{cls.__name__}"
    _RootAlias.__doc__ = f"Deprecated-root-import wrapper for :class:`torchmetrics_tpu.{domain}.{cls.__name__}`."
    return _RootAlias
