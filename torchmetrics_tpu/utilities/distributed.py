"""Distributed helpers + generic reductions.

Capability parity: reference ``src/torchmetrics/utilities/distributed.py`` (146 LoC):
``reduce:20``, ``class_reduce:46``, ``gather_all_tensors:96``. The gather itself lives
in ``torchmetrics_tpu.parallel.sync`` (the XLA-collective communication backend) and is
re-exported here so reference import paths keep working.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.parallel.sync import (  # noqa: F401  (re-export)
    EvalMesh,
    _simple_gather_all_tensors,
    gather_all_tensors,
    jit_distributed_available,
)

Array = jax.Array


def reduce(x: Array, reduction: Optional[str]) -> Array:
    """Reduce a tensor by 'elementwise_mean' | 'sum' | 'none' (reference ``distributed.py:20-43``)."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "none" or reduction is None:
        return x
    if reduction == "sum":
        return jnp.sum(x)
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction reduction 'micro'|'macro'|'weighted'|'none' (reference ``distributed.py:46-87``)."""
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    # We need to take care of instances where the denom can be 0 — for some classes the fraction becomes nan
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")
