"""Safe math helpers + trapezoidal AUC.

Capability parity: reference ``src/torchmetrics/utilities/compute.py:22-129``. All
functions are pure jnp → jit-safe; the division/xlogy guards use ``jnp.where`` double-
where so gradients stay finite under XLA (the reference relies on eager masking).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul that broadcasts over leading dims (reference ``compute.py:22-30``)."""
    return jnp.matmul(x, y)


def _safe_xlogy(x: Array, y: Array) -> Array:
    """``x * log(y)`` with 0*log(0)=0 (reference ``compute.py:33-42``)."""
    y_safe = jnp.where(x == 0, jnp.ones_like(y), y)
    return jnp.where(x == 0, jnp.zeros_like(x * jnp.log(y_safe)), x * jnp.log(y_safe))


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Division with 0/0 -> ``zero_division`` (reference ``compute.py:45-55``)."""
    num = num if jnp.issubdtype(jnp.asarray(num).dtype, jnp.floating) else jnp.asarray(num, jnp.float32)
    denom = denom if jnp.issubdtype(jnp.asarray(denom).dtype, jnp.floating) else jnp.asarray(denom, jnp.float32)
    denom_safe = jnp.where(denom == 0, jnp.ones_like(denom), denom)
    return jnp.where(denom == 0, jnp.full_like(num / denom_safe, zero_division), num / denom_safe)


def _sum_axis(x: Array, axis: int) -> Array:
    """``x.sum(axis)`` that is a no-op on 0-d arrays (torch allows dim=0 on scalars; jnp doesn't)."""
    return jnp.sum(x, axis=axis) if jnp.ndim(x) else x


def _adjust_weights_safe_divide(
    score: Array, average: Optional[str], multilabel: bool, tp: Array, fp: Array, fn: Array
) -> Array:
    """Weighted/macro reduction of per-class scores (reference ``compute.py:58-74``)."""
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = tp + fn
    else:
        weights = jnp.ones_like(score)
        if not multilabel:
            weights = jnp.where(tp + fp + fn == 0, 0.0, weights)
    # reduce over the class axis only — samplewise inputs are (N, C) and keep their N
    return jnp.sum(_safe_divide(weights * score, jnp.sum(weights, axis=-1, keepdims=True)), axis=-1)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """1D linear interpolation (reference ``compute.py:77-98``) — jnp.interp native."""
    return jnp.interp(x, xp, fp)


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    """Trapezoidal area assuming monotone ``x`` (reference ``compute.py:101-108``)."""
    dx = jnp.diff(x, axis=axis)
    y_avg = (jax.lax.slice_in_dim(y, 1, None, axis=axis) + jax.lax.slice_in_dim(y, 0, -1, axis=axis)) / 2.0
    return jnp.sum(y_avg * dx, axis=axis) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """Trapezoidal AUC with optional sort and direction detection (reference ``compute.py:111-129``).

    Direction is resolved with ``jnp.where`` instead of a host branch so the whole AUC
    stays inside one XLA graph (monotonicity *errors* are only raised in eager paths).
    """
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
    dx = jnp.diff(x)
    direction = jnp.where(jnp.all(dx <= 0), -1.0, 1.0)
    return _auc_compute_without_check(x, y, direction)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Public AUC (reference ``compute.py:117-129``)."""
    if x.ndim != 1 or y.ndim != 1:
        raise ValueError(f"Expected 1D arrays, got x.ndim={x.ndim}, y.ndim={y.ndim}")
    if x.shape[0] != y.shape[0]:
        raise ValueError("x and y must have the same length")
    return _auc_compute(x, y, reorder=reorder)
