"""Plot subsystem (matplotlib optional).

Capability parity: reference ``src/torchmetrics/utilities/plot.py`` (320 LoC):
``plot_single_or_multi_val:61``, ``plot_confusion_matrix:192``, ``plot_curve:260``.
Arrays are converted to numpy on the host before plotting — plotting is never on the
device path.
"""

from __future__ import annotations

from itertools import product
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utilities.imports import _MATPLOTLIB_AVAILABLE

if _MATPLOTLIB_AVAILABLE:
    import matplotlib
    import matplotlib.pyplot as plt

    _PLOT_OUT_TYPE = Tuple["plt.Figure", Union["matplotlib.axes.Axes", np.ndarray]]
    _AX_TYPE = "matplotlib.axes.Axes"
else:
    _PLOT_OUT_TYPE = Tuple[object, object]  # type: ignore[misc]
    _AX_TYPE = object  # type: ignore[misc]


def _error_on_missing_matplotlib() -> None:
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(
            "Plot function expects `matplotlib` to be installed. Install with `pip install matplotlib`"
        )


def _to_np(x: Any) -> np.ndarray:
    return np.asarray(x)


def plot_single_or_multi_val(
    val: Union[Any, Sequence[Any], Dict[str, Any], Sequence[Dict[str, Any]]],
    ax: Optional[Any] = None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> "_PLOT_OUT_TYPE":
    """Plot a single metric value or a sequence of values over steps (reference ``plot.py:61-189``)."""
    _error_on_missing_matplotlib()
    fig, ax = (plt.subplots() if ax is None else (ax.get_figure(), ax))
    ax.get_xaxis().set_visible(True)
    ax.get_yaxis().set_visible(True)

    if isinstance(val, dict):
        for i, (key, item) in enumerate(val.items()):
            item = _to_np(item)
            if item.ndim == 0:
                ax.plot(i, item, marker="o", markersize=10, linestyle="None", label=key)
            else:
                ax.plot(item.flatten(), marker="o", markersize=10, linestyle="-", label=key)
    elif isinstance(val, (list, tuple)) and all(isinstance(v, dict) for v in val):
        keys = list(val[0].keys())
        for key in keys:
            series = np.stack([_to_np(v[key]).reshape(-1) for v in val])
            if series.shape[1] == 1:
                ax.plot(series[:, 0], marker="o", markersize=10, linestyle="-", label=key)
            else:
                for c in range(series.shape[1]):
                    ax.plot(series[:, c], marker="o", markersize=10, linestyle="-", label=f"{key}_{c}")
    elif isinstance(val, (list, tuple)):
        series = np.stack([_to_np(v).reshape(-1) for v in val])
        n_steps, n_vals = series.shape
        if n_vals == 1:
            ax.plot(np.arange(n_steps), series[:, 0], marker="o", markersize=10, linestyle="-")
        else:
            for c in range(n_vals):
                label = f"{legend_name}_{c}" if legend_name else str(c)
                ax.plot(np.arange(n_steps), series[:, c], marker="o", markersize=10, linestyle="-", label=label)
    else:
        arr = _to_np(val)
        if arr.ndim == 0:
            ax.plot([0], [arr], marker="o", markersize=10, linestyle="None")
        else:
            arr = arr.flatten()
            for i, v in enumerate(arr):
                label = f"{legend_name}_{i}" if legend_name else str(i)
                ax.plot(i, v, marker="o", markersize=10, linestyle="None", label=label)

    handles, labels = ax.get_legend_handles_labels()
    if labels:
        ax.legend(loc="best")

    ylim = ax.get_ylim()
    if lower_bound is not None or upper_bound is not None:
        ax.set_ylim(
            bottom=lower_bound if lower_bound is not None else ylim[0],
            top=upper_bound if upper_bound is not None else ylim[1],
        )
    if name is not None:
        ax.set_title(name)
    ax.set_xlabel("Step")
    ax.set_ylabel("Value")
    return fig, ax


def trim_axs(axs: Any, nb: int) -> Any:
    """Trim a grid of axes to ``nb`` (reference ``plot.py:...``)."""
    if isinstance(axs, np.ndarray):
        axs = axs.flat
        for ax in axs[nb:]:
            ax.remove()
        return axs[:nb]
    return axs


def plot_confusion_matrix(
    confmat: Any,
    ax: Optional[Any] = None,
    add_text: bool = True,
    labels: Optional[List[Union[str, int]]] = None,
    cmap: Optional[str] = None,
) -> "_PLOT_OUT_TYPE":
    """Heatmap of a (num_classes, num_classes) or (N, 2, 2) confusion matrix (reference ``plot.py:192-257``)."""
    _error_on_missing_matplotlib()
    confmat = _to_np(confmat)
    multilabel = confmat.ndim == 3
    if multilabel:  # (N, 2, 2) per-label confmats
        nb, n_classes = confmat.shape[0], 2
        rows, cols = int(np.ceil(np.sqrt(nb))), int(np.round(np.sqrt(nb)))
    else:
        nb, n_classes = 1, confmat.shape[0]
        rows, cols = 1, 1
        confmat = confmat[None]

    # per-class tick labels only make sense for the single (C, C) case (ref ``plot.py:219-221``)
    if labels is not None and not multilabel and len(labels) != n_classes:
        raise ValueError("Expected number of elements in arg `labels` to match number of labels in confmat")
    labels = labels if labels is not None else np.arange(n_classes).tolist()

    if ax is None:
        fig, axs = plt.subplots(nrows=rows, ncols=cols)
    else:
        fig = ax.get_figure()
        axs = ax
    axs = trim_axs(axs, nb) if isinstance(axs, np.ndarray) else [axs]

    for i in range(nb):
        ax_i = axs[i] if nb > 1 else axs[0]
        if nb > 1:
            ax_i.set_title(f"Label {i}", fontsize=15)
        ax_i.imshow(confmat[i], cmap=cmap)
        ax_i.set_xlabel("Predicted class", fontsize=15)
        ax_i.set_ylabel("True class", fontsize=15)
        ax_i.set_xticks(list(range(n_classes)))
        ax_i.set_yticks(list(range(n_classes)))
        ax_i.set_xticklabels(labels, rotation=45, fontsize=10)
        ax_i.set_yticklabels(labels, rotation=25, fontsize=10)
        if add_text:
            for ii, jj in product(range(n_classes), range(n_classes)):
                val = confmat[i, ii, jj]
                txt = f"{val.item():.2f}" if np.issubdtype(confmat.dtype, np.floating) else str(int(val))
                ax_i.text(jj, ii, txt, ha="center", va="center", fontsize=15)
    return fig, axs if nb > 1 else axs[0]


def plot_curve(
    curve: Tuple[Any, ...],
    score: Optional[Any] = None,
    ax: Optional[Any] = None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> "_PLOT_OUT_TYPE":
    """Plot a (x, y, thresholds)-style curve e.g. ROC/PR (reference ``plot.py:260-320``)."""
    _error_on_missing_matplotlib()
    if len(curve) < 2:
        raise ValueError("Expected 2 or more elements in curve object")
    x, y = _to_np(curve[0]), _to_np(curve[1])
    fig, ax = (plt.subplots() if ax is None else (ax.get_figure(), ax))

    if x.ndim == 1 and y.ndim == 1:
        label = f"AUC={score.item():0.3f}" if score is not None else None
        ax.plot(x, y, linestyle="-", linewidth=2, label=label)
        if label is not None:
            ax.legend()
    elif (isinstance(curve[0], (list, tuple)) and isinstance(curve[1], (list, tuple))) or (x.ndim == 2 and y.ndim == 2):
        n = len(curve[0])
        for i in range(n):
            xi, yi = _to_np(curve[0][i]), _to_np(curve[1][i])
            label = f"{legend_name}_{i}" if legend_name else str(i)
            label += f" AUC={score[i].item():0.3f}" if score is not None else ""
            ax.plot(xi, yi, label=label)
        ax.legend()
    else:
        raise ValueError(
            f"Unknown format for argument `curve`. Expected 2 lists of 1D arrays or 2D arrays, got {x.ndim}D/{y.ndim}D"
        )
    ax.grid(True)
    if label_names is not None:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if name is not None:
        ax.set_title(name)
    return fig, ax
