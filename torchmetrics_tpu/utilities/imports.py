"""Optional-dependency availability flags.

Capability parity: reference ``src/torchmetrics/utilities/imports.py:23-55`` keeps ~25
flags gating optional metric exports. The TPU build's hard deps are jax/flax/optax
(baked in); everything else is probed lazily so the framework imports with zero optional
packages installed.
"""

from __future__ import annotations

import importlib.util
import operator
import sys


def _package_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_PYTHON_GREATER_EQUAL_3_8 = sys.version_info >= (3, 8)

_JAX_AVAILABLE = _package_available("jax")
_FLAX_AVAILABLE = _package_available("flax")
_TORCH_AVAILABLE = _package_available("torch")
_NUMPY_AVAILABLE = _package_available("numpy")
_SCIPY_AVAILABLE = _package_available("scipy")
_SKLEARN_AVAILABLE = _package_available("sklearn")
_MATPLOTLIB_AVAILABLE = _package_available("matplotlib")
_TRANSFORMERS_AVAILABLE = _package_available("transformers")
_NLTK_AVAILABLE = _package_available("nltk")
_REGEX_AVAILABLE = _package_available("regex")
_PESQ_AVAILABLE = _package_available("pesq")
_PYSTOI_AVAILABLE = _package_available("pystoi")
_PYCOCOTOOLS_AVAILABLE = _package_available("pycocotools")
_TORCHVISION_AVAILABLE = _package_available("torchvision")
_TORCH_FIDELITY_AVAILABLE = _package_available("torch_fidelity")
_LPIPS_AVAILABLE = _package_available("lpips")
_FAST_BSS_EVAL_AVAILABLE = _package_available("fast_bss_eval")
_MECAB_AVAILABLE = _package_available("MeCab")
_IPADIC_AVAILABLE = _package_available("ipadic")
_SENTENCEPIECE_AVAILABLE = _package_available("sentencepiece")
_PANDAS_AVAILABLE = _package_available("pandas")
_MULTIPROCESSING_AVAILABLE = True

# The reference special-cases XLA (``imports.py:53``); for us XLA *is* the substrate.
_XLA_AVAILABLE = True
