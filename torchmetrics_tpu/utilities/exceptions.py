"""Framework exceptions.

Capability parity: reference ``src/torchmetrics/utilities/exceptions.py:1-21``.
"""


class TorchMetricsUserError(Exception):
    """Error raised when a user misuses the metric API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on suspicious-but-legal metric API usage."""
