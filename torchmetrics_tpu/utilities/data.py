"""Core data/reduction primitives on jax.numpy.

Capability parity: reference ``src/torchmetrics/utilities/data.py`` (278 LoC). Key
TPU-first divergences:

* ``_bincount`` — the reference falls back to a Python loop under XLA
  (``data.py:211-241``); here bincount is a single ``scatter-add`` (``.at[].add``),
  which XLA lowers deterministically and tiles onto the VPU. No fallback needed.
* ``dim_zero_cat`` accepts tuples/lists of arrays (our "cat" states are host-managed
  lists of device arrays) and concatenates with one XLA op.
* ``apply_to_collection`` is implemented on ``jax.tree_util`` so arbitrary pytrees of
  states map in one pass.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array

METRIC_EPS = 1e-6


def dim_zero_cat(x: Union[Array, Sequence[Array]]) -> Array:
    """Concatenate a (list of) array(s) along dim 0 (reference ``data.py:28-38``)."""
    if isinstance(x, (jnp.ndarray, jax.Array)) and not isinstance(x, (list, tuple)):
        return x
    x = [y if y.ndim else y.reshape(1) for y in x]
    if not x:
        raise ValueError("No samples to concatenate")
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    """Summation along dim 0 (reference ``data.py:41-43``)."""
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    """Average along dim 0 (reference ``data.py:46-48``)."""
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    """Max along dim 0 (reference ``data.py:51-53``)."""
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    """Min along dim 0 (reference ``data.py:56-58``)."""
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten list of lists into one list (reference ``data.py:61-63``)."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: dict) -> Tuple[dict, bool]:
    """Flatten dict of dicts into one level (reference ``data.py:66-72``)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, duplicates


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert integer labels ``(N, ...)`` to one-hot ``(N, C, ...)``.

    Reference ``data.py:75-106`` uses ``scatter_``; here ``jax.nn.one_hot`` emits a
    compare-broadcast that XLA fuses (MXU/VPU friendly, no scatter at all).
    """
    if num_classes is None:
        num_classes = int(jnp.max(label_tensor)) + 1
    oh = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int64 if label_tensor.dtype == jnp.int64 else jnp.int32)
    # (N, ..., C) -> (N, C, ...)
    return jnp.moveaxis(oh, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k entries along ``dim`` (reference ``data.py:109-132``)."""
    if topk == 1:  # argmax fast path — single reduce, no sort
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    _, idx = jax.lax.top_k(jnp.moveaxis(prob_tensor, dim, -1), topk)
    mask = jnp.zeros(jnp.moveaxis(prob_tensor, dim, -1).shape, dtype=jnp.int32)
    mask = jnp.put_along_axis(mask, idx, 1, axis=-1, inplace=False)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities/logits to categorical labels via argmax (reference ``data.py:135-150``)."""
    return jnp.argmax(x, axis=argmax_dim)


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    wrong_dtype: Optional[Union[type, tuple]] = None,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all elements of ``dtype`` (reference ``data.py:153-200``)."""
    if isinstance(data, dtype) and (wrong_dtype is None or not isinstance(data, wrong_dtype)):
        return function(data, *args, **kwargs)
    if isinstance(data, dict):
        return type(data)({k: apply_to_collection(v, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for k, v in data.items()})
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return type(data)(*(apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data))
    if isinstance(data, (list, tuple)):
        return type(data)(apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data)
    return data


def _squeeze_scalar_element_tensor(x: Array) -> Array:
    return x.reshape(()) if x.size == 1 else x


def _squeeze_if_scalar(data: Any) -> Any:
    """Squeeze size-1 arrays in a collection to scalars (reference ``data.py:207-208``)."""
    return apply_to_collection(data, (jnp.ndarray, jax.Array), _squeeze_scalar_element_tensor)


def _bincount(x: Array, minlength: Optional[int] = None, weights: Optional[Array] = None) -> Array:
    """Deterministic (optionally weighted) bincount as one scatter-add.

    The reference needs a Python-loop fallback on XLA/MPS/deterministic-CUDA
    (``data.py:211-241``); here bincount is always ``zeros.at[x].add(...)`` — one
    fused scatter XLA lowers deterministically — so confusion-matrix and
    histogram updates stay in-graph instead of O(bins) host iterations.
    Negative / out-of-range indices are dropped (``mode="drop"``), which is what
    the ignore-index masking upstream relies on.

    ``minlength`` must be static for XLA. Omitting it requires reading
    ``max(x)`` on the host, which cannot happen under a trace — inside ``jit``
    (or the fused update engine) pass the bin count explicitly.
    """
    if minlength is None:
        if isinstance(x, jax.core.Tracer):
            raise ValueError(
                "_bincount under jit/trace requires a static `minlength`; deriving it"
                " from max(x) needs a host readback the graph cannot contain."
            )
        minlength = int(jnp.max(x)) + 1 if x.size else 1
    updates = jnp.ones_like(x, dtype=jnp.int32) if weights is None else weights.astype(jnp.int32)
    # negative indices would WRAP (jax .at[] keeps numpy indexing semantics);
    # zero their updates so masked/ignored entries truly drop, matching the
    # mode="drop" treatment of too-large indices
    updates = jnp.where(x < 0, 0, updates)
    return jnp.zeros(minlength, dtype=jnp.int32).at[x].add(updates, mode="drop")


def _cumsum(x: Array, dim: int = 0) -> Array:
    """Cumulative sum; XLA is deterministic so no CPU round-trip (reference ``data.py:244-253``)."""
    return jnp.cumsum(x, axis=dim)


def _flexible_bincount(x: Array) -> Array:
    """Bincount over the *unique values present* (reference ``data.py:256-271``).

    Returns counts for each unique value in sorted order — used by retrieval group-by.
    """
    _, inverse, counts = jnp.unique(x, return_inverse=True, return_counts=True)
    del inverse
    return counts


def allclose(tensor1: Array, tensor2: Array, atol: float = 1e-8, rtol: float = 1e-5) -> bool:
    """Shape-aware allclose (reference ``data.py:274-278``)."""
    if tensor1.shape != tensor2.shape:
        return False
    return bool(jnp.allclose(tensor1, tensor2, atol=atol, rtol=rtol))
