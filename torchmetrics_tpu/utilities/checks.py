"""Input validation helpers.

Capability parity: reference ``src/torchmetrics/utilities/checks.py`` (790 LoC). All
checks here run on the host *outside* jit (they raise Python exceptions); metrics gate
them behind ``validate_args`` exactly like the reference so the jitted hot path carries
zero validation overhead.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if shapes differ (reference ``checks.py:39-44``)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _is_floating(x: Array) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _is_integral(x: Array) -> bool:
    d = jnp.asarray(x).dtype
    return jnp.issubdtype(d, jnp.integer) or jnp.issubdtype(d, jnp.bool_)


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    """Check and flatten retrieval functional inputs (reference ``checks.py:478-508``)."""
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if preds.size == 0:
        raise ValueError("`preds` and `target` must be non-empty")
    if not _is_floating(preds):
        raise ValueError("`preds` must be a tensor of floats")
    return _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)


def _check_retrieval_inputs(
    indexes: Array, preds: Array, target: Array, allow_non_binary_target: bool = False, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """Check retrieval (indexes, preds, target) triple (reference ``checks.py:535-580``)."""
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if indexes.size == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty")
    if not _is_integral(indexes) or jnp.issubdtype(indexes.dtype, jnp.bool_):
        raise ValueError("`indexes` must be a tensor of long integers")
    if ignore_index is not None:
        valid = target != ignore_index
        indexes, preds, target = indexes[valid], preds[valid], target[valid]
    if not _is_floating(preds):
        raise ValueError("`preds` must be a tensor of floats")
    preds, target = _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)
    return indexes.reshape(-1).astype(jnp.int32), preds, target


def _check_retrieval_target_and_prediction_types(
    preds: Array, target: Array, allow_non_binary_target: bool
) -> Tuple[Array, Array]:
    """Reference ``checks.py:583-607``."""
    if _is_floating(target):
        if not allow_non_binary_target:
            raise ValueError("`target` must be a tensor of booleans or integers")
    elif not _is_integral(target):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if not allow_non_binary_target and bool(jnp.any((target > 1) | (target < 0))):
        raise ValueError("`target` must contain `binary` values")
    t = target.astype(jnp.float32) if _is_floating(target) else target.astype(jnp.int32)
    return preds.reshape(-1).astype(jnp.float32), t.reshape(-1)


def check_forward_full_state_property(
    metric_class: type,
    init_args: Optional[dict] = None,
    input_args: Optional[dict] = None,
    num_update_to_compare: Tuple[int, ...] = (10, 100, 1000),
    reps: int = 5,
) -> None:
    """Empirically compare full-state vs reduced-state ``forward`` (reference ``checks.py:629-759``).

    Checks that the two forward paths agree numerically and reports which is faster, so
    metric authors can set ``full_state_update`` correctly.
    """
    import time

    init_args = init_args or {}
    input_args = input_args or {}

    fullstate = type("_FullState", (metric_class,), {"full_state_update": True})(**init_args)
    partstate = type("_PartState", (metric_class,), {"full_state_update": False})(**init_args)

    equal = True
    for _ in range(max(num_update_to_compare)):
        out1 = fullstate(**input_args)
        out2 = partstate(**input_args)
        equal = equal and bool(
            jax.tree_util.tree_all(
                jax.tree_util.tree_map(lambda a, b: np.allclose(np.asarray(a), np.asarray(b), atol=1e-6), out1, out2)
            )
        )
    if not equal:
        print("Full state and reduced state `forward` disagree: `full_state_update=True` is required.")
        return

    res = [[], []]
    for i, metric in enumerate([fullstate, partstate]):
        for _ in range(reps):
            start = time.perf_counter()
            for _ in range(min(num_update_to_compare)):
                metric(**input_args)
            res[i].append(time.perf_counter() - start)
    faster = bool(np.mean(res[1]) < np.mean(res[0]))
    print(
        f"Full state update: {np.mean(res[0]):.4g}s, reduced state update: {np.mean(res[1]):.4g}s."
        f" Recommended setting: `full_state_update={not faster}`."
    )


def _allclose_recursive(res1: Any, res2: Any, atol: float = 1e-6) -> bool:
    """Pytree-recursive allclose (reference ``checks.py:612-626``)."""
    leaves1 = jax.tree_util.tree_leaves(res1)
    leaves2 = jax.tree_util.tree_leaves(res2)
    if len(leaves1) != len(leaves2):
        return False
    return all(np.allclose(np.asarray(a), np.asarray(b), atol=atol) for a, b in zip(leaves1, leaves2))
