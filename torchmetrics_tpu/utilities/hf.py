"""Hugging Face model loading for the model-backed text/multimodal metrics.

The reference loads ``transformers`` AutoModels directly inside BERTScore/InfoLM/
CLIPScore (``text/bert.py:192-195``, ``functional/text/infolm.py``,
``multimodal/clip_score.py``). The TPU build routes every such load through here:

- Flax-first: ``FlaxAuto*`` classes run the transformer natively under JAX/XLA on the
  TPU; if a checkpoint only ships torch weights, ``from_pt=True`` converts them.
- Torch fallback: when no Flax head exists for an architecture, the torch model runs
  host-side and features are shipped to device (the reference runs torch everywhere).
- Offline-clean errors: in a no-egress environment ``from_pretrained`` of an uncached
  hub id fails — that surface is turned into one actionable message (cache the model
  or pass a local directory / injected callables) instead of an HTTP traceback.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp


@lru_cache(maxsize=8)
def load_hf_model_and_tokenizer(model_name_or_path: str, auto_cls_name: str = "FlaxAutoModel") -> Tuple[Any, Any]:
    """Cached ``(model, tokenizer)`` per checkpoint id/path.

    Metric ``forward``/``compute`` call into the functional API per step; without this
    cache every step would re-deserialize the checkpoint and retrace the forward
    (mirrors ``_default_lpips_network``/``_default_fid_extractor`` in the image stack).
    """
    return load_hf_flax_model(model_name_or_path, auto_cls_name), load_hf_tokenizer(model_name_or_path)


def _load_error(model_name_or_path: str, exc: Exception) -> ModuleNotFoundError:
    return ModuleNotFoundError(
        f"Could not load pretrained weights for `{model_name_or_path!r}`: {exc.__class__.__name__}. In an"
        " offline environment the weights must already be cached (HF_HOME) or `model_name_or_path` must be a"
        " local directory created with `save_pretrained`. Alternatively inject the network directly (pass a"
        " callable model + tokenizer), as in the reference's own-model example."
    )


def _is_repo_not_found(exc: Exception) -> bool:
    """True when the failure means the checkpoint ID is unresolvable (vs a weights-format issue).

    Matched by exception class name (``huggingface_hub`` raises dedicated types) plus
    the two stable identifier-level messages, so a wording tweak in format-level
    errors can never suppress the ``from_pt`` conversion retry.

    A top-level error that explicitly names the missing FLAX weights
    (``flax_model``/``from_pt``) is a weights-format failure no matter what sits
    in its ``__cause__``/``__context__`` chain: some transformers versions
    surface a cached torch-only checkpoint in offline mode as a
    missing-flax_model error whose chain carries ``LocalEntryNotFoundError`` —
    the ``from_pt`` retry succeeds FROM CACHE there, so offline/connection
    names in the chain must not veto it.
    """
    msg = str(exc)
    if "flax_model" in msg or "from_pt" in msg:
        return False
    names = set()
    stack, seen = [exc], set()
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        names.add(type(e).__name__)
        stack += [e.__cause__, e.__context__]
    if names & {
        "RepositoryNotFoundError",
        "RevisionNotFoundError",
        "GatedRepoError",
        "HFValidationError",
        # offline/no-egress: the id may exist but cannot be fetched — a from_pt
        # retry would just pay another full network timeout
        "LocalEntryNotFoundError",
        "OfflineModeIsEnabled",
        "ConnectionError",
        "ConnectTimeout",
    }:
        return True
    return (
        "is not a valid model identifier" in msg
        or "is not a local folder" in msg
        or "offline mode" in msg.lower()
    )


def load_hf_tokenizer(model_name_or_path: str) -> Any:
    """AutoTokenizer with offline-clean failure."""
    from transformers import AutoTokenizer

    try:
        return AutoTokenizer.from_pretrained(model_name_or_path)
    except Exception as exc:  # noqa: BLE001 — hub raises OSError/EnvironmentError/HTTPError variants
        raise _load_error(model_name_or_path, exc) from exc


def load_hf_flax_model(model_name_or_path: str, auto_cls_name: str = "FlaxAutoModel") -> Any:
    """Load a Flax transformer (converting torch weights when needed), else torch fallback.

    Returns a model object with ``__call__(input_ids, attention_mask, ...)``; the
    ``framework`` attribute is set to ``"flax"`` or ``"pt"``.
    """
    import transformers

    flax_cls = getattr(transformers, auto_cls_name, None)
    first_exc: Optional[Exception] = None
    if flax_cls is not None:
        try:
            # transformers models carry a read-only `.framework` ("flax"/"pt")
            return flax_cls.from_pretrained(model_name_or_path)
        except Exception as exc:  # noqa: BLE001 — hub raises OSError/ValueError variants
            first_exc = exc
            # A torch-only checkpoint makes the plain Flax load fail, but the error
            # wording varies across transformers versions — sniffing the message would
            # silently lose the Flax-first path on a phrasing change. Retry with
            # from_pt=True by default, skipping only errors that clearly say the
            # CHECKPOINT ID itself cannot be resolved (so a missing/uncached id pays
            # two slow hub attempts, not three, while every weights-format failure
            # still gets the conversion attempt regardless of phrasing).
            if not _is_repo_not_found(exc):
                try:
                    return flax_cls.from_pretrained(model_name_or_path, from_pt=True)
                except Exception as exc2:  # noqa: BLE001
                    if "flax_model" in str(exc) or "from_pt" in str(exc):
                        # the first error explicitly named the missing Flax weights, so
                        # the conversion failure is the more informative one to surface
                        first_exc = exc2
    torch_cls_name = auto_cls_name.replace("Flax", "")
    torch_cls = getattr(transformers, torch_cls_name, None)
    if torch_cls is None:
        raise _load_error(
            model_name_or_path,
            first_exc or AttributeError(f"transformers has no auto class {torch_cls_name!r}"),
        )
    try:
        model = torch_cls.from_pretrained(model_name_or_path)
    except Exception as exc:  # noqa: BLE001
        raise _load_error(model_name_or_path, first_exc or exc) from exc
    model.eval()
    return model


def hf_embedding_forward(model: Any, num_layers: Optional[int] = None) -> Callable:
    """Wrap a loaded HF model as ``(input_ids, attention_mask) -> (N, L, D) jnp array``.

    ``num_layers`` selects ``hidden_states[num_layers]`` (the reference's layer pick,
    ``functional/text/bert.py``); ``None`` uses the last hidden state.
    """
    framework = getattr(model, "framework", "flax")

    if framework == "pt":

        def forward(input_ids, attention_mask):
            import numpy as np
            import torch

            with torch.no_grad():
                out = model(
                    input_ids=torch.as_tensor(np.asarray(input_ids)),
                    attention_mask=torch.as_tensor(np.asarray(attention_mask)),
                    output_hidden_states=num_layers is not None,
                )
            hidden = out.hidden_states[num_layers] if num_layers is not None else out.last_hidden_state
            return jnp.asarray(hidden.numpy())

        return forward

    def forward(input_ids, attention_mask):
        out = model(
            input_ids=jnp.asarray(input_ids),
            attention_mask=jnp.asarray(attention_mask),
            output_hidden_states=num_layers is not None,
        )
        hidden = out.hidden_states[num_layers] if num_layers is not None else out.last_hidden_state
        return jnp.asarray(hidden)

    return forward


def hf_logits_forward(model: Any) -> Callable:
    """Wrap a loaded HF masked-LM as ``(input_ids, attention_mask) -> (N, L, V) logits``."""
    framework = getattr(model, "framework", "flax")

    if framework == "pt":

        def forward(input_ids, attention_mask):
            import numpy as np
            import torch

            with torch.no_grad():
                out = model(
                    input_ids=torch.as_tensor(np.asarray(input_ids)),
                    attention_mask=torch.as_tensor(np.asarray(attention_mask)),
                )
            return jnp.asarray(out.logits.numpy())

        return forward

    def forward(input_ids, attention_mask):
        out = model(input_ids=jnp.asarray(input_ids), attention_mask=jnp.asarray(attention_mask))
        return jnp.asarray(out.logits)

    return forward


def model_max_length(model: Any, max_length: int) -> int:
    """Cap a requested sequence length by the model's position-embedding capacity.

    Padding past ``max_position_embeddings`` feeds out-of-range position ids into the
    embedding lookup, which silently corrupts every token's attention output.
    """
    cap = getattr(getattr(model, "config", None), "max_position_embeddings", None)
    return min(max_length, cap) if isinstance(cap, int) and cap > 0 else max_length


def hf_tokenize(
    tokenizer: Any, sentences, max_length: int = 512, padding: str = "max_length"
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Tokenize a list of sentences to padded ``(input_ids, attention_mask)`` arrays."""
    enc = tokenizer(
        list(sentences),
        padding=padding,
        truncation=True,
        max_length=max_length,
        return_tensors="np",
    )
    return jnp.asarray(enc["input_ids"]), jnp.asarray(enc["attention_mask"])
