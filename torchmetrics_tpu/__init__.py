"""TPU-native metrics framework (capability parity with the torchmetrics reference).

Flat public API mirroring reference ``src/torchmetrics/__init__.py`` — grows as domains
land.
"""

from torchmetrics_tpu.__about__ import __version__
from torchmetrics_tpu.aggregation import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    RunningMean,
    RunningSum,
    SumMetric,
)
from torchmetrics_tpu.classification import (
    AUROC,
    Accuracy,
    AveragePrecision,
    CalibrationError,
    CohenKappa,
    Dice,
    ExactMatch,
    HingeLoss,
    JaccardIndex,
    MatthewsCorrCoef,
    PrecisionRecallCurve,
    ROC,
    ConfusionMatrix,
    F1Score,
    FBetaScore,
    HammingDistance,
    Precision,
    Recall,
    Specificity,
    StatScores,
)
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import CompositionalMetric, Metric
from torchmetrics_tpu.wrappers import (
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
    MultitaskWrapper,
    Running,
)

__all__ = [
    "AUROC",
    "Accuracy",
    "AveragePrecision",
    "CalibrationError",
    "CohenKappa",
    "Dice",
    "ExactMatch",
    "HingeLoss",
    "JaccardIndex",
    "MatthewsCorrCoef",
    "PrecisionRecallCurve",
    "ROC",
    "CatMetric",
    "CompositionalMetric",
    "ConfusionMatrix",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "MaxMetric",
    "MeanMetric",
    "BootStrapper",
    "ClasswiseWrapper",
    "MetricTracker",
    "MinMaxMetric",
    "MultioutputWrapper",
    "MultitaskWrapper",
    "Running",
    "Metric",
    "MetricCollection",
    "MinMetric",
    "Precision",
    "Recall",
    "RunningMean",
    "RunningSum",
    "Specificity",
    "StatScores",
    "SumMetric",
    "__version__",
]
