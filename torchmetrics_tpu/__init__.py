"""TPU-native metrics framework (capability parity with the torchmetrics reference).

Flat public API mirroring reference ``src/torchmetrics/__init__.py`` — grows as domains
land.
"""

from torchmetrics_tpu.__about__ import __version__
from torchmetrics_tpu.metric import CompositionalMetric, Metric

__all__ = [
    "CompositionalMetric",
    "Metric",
    "__version__",
]
