"""RetrievalMetric base (reference ``retrieval/base.py:25-160``).

TPU-first redesign of the grouped compute: instead of the reference's per-query Python
loop over ``torch.split`` slices, the epoch's ragged ``(indexes, preds, target)`` rows
are packed once into dense rank-ordered ``(num_queries, max_len)`` matrices (pads score
``-inf`` / relevance 0), and every built-in metric evaluates as batched ``axis=-1``
reductions over the whole matrix — one XLA computation for the entire epoch, no
data-dependent control flow. Custom subclasses that override the reference-style
per-query ``_metric`` hook still work: the base falls back to the grouped loop for them.
"""

from __future__ import annotations

from abc import ABC
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.checks import _check_retrieval_inputs
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


def _pack_query_groups(indexes: Array, preds: Array, target: Array) -> Tuple[Array, Array, Array]:
    """Rank-sorted dense matrices from flat grouped rows.

    Rows are queries, columns are within-query descending-score rank. Returns
    ``(preds_mat, target_mat, valid)`` with pads at ``-inf`` / 0 / False.
    """
    idx = np.asarray(indexes)
    p = np.asarray(preds)
    t = np.asarray(target)
    order = np.lexsort((-p, idx))
    idx, p, t = idx[order], p[order], t[order]
    _, counts = np.unique(idx, return_counts=True)
    n_queries, max_len = len(counts), int(counts.max())
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    ranks = np.arange(len(idx)) - np.repeat(starts, counts)
    rows = np.repeat(np.arange(n_queries), counts)

    preds_mat = np.full((n_queries, max_len), -np.inf, dtype=np.float32)
    preds_mat[rows, ranks] = p
    target_mat = np.zeros((n_queries, max_len), dtype=np.float32)
    target_mat[rows, ranks] = t
    valid = np.zeros((n_queries, max_len), dtype=bool)
    valid[rows, ranks] = True
    return jnp.asarray(preds_mat), jnp.asarray(target_mat), jnp.asarray(valid)


class RetrievalMetric(Metric, ABC):
    """Query-grouped retrieval metric over float scores and binary relevance."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False

    indexes: List[Array]
    preds: List[Array]
    target: List[Array]

    # which side defines an "empty" query: positives for every metric except fall-out
    _empty_on_negatives: bool = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        self.add_state("indexes", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Check shape/dtypes, flatten, and buffer (reference ``base.py:100-112``)."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            jnp.asarray(indexes),
            jnp.asarray(preds),
            jnp.asarray(target),
            allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Group by query and fold per-query scores by ``empty_target_action``."""
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        preds_mat, target_mat, valid = _pack_query_groups(indexes, preds, target)
        scores = self._metric_dense(preds_mat, target_mat, valid)

        if self._empty_on_negatives:
            empty = ((1 - target_mat) * valid).sum(axis=-1) == 0
        else:
            empty = target_mat.sum(axis=-1) == 0

        if self.empty_target_action == "error" and bool(empty.any()):
            side = "negative" if self._empty_on_negatives else "positive"
            raise ValueError(f"`compute` method was provided with a query with no {side} target.")
        if self.empty_target_action == "skip":
            kept = jnp.where(~empty, scores, 0.0)
            n_kept = (~empty).sum()
            return jnp.where(n_kept == 0, 0.0, kept.sum() / jnp.where(n_kept == 0, 1, n_kept))
        fill = 1.0 if self.empty_target_action == "pos" else 0.0
        return jnp.where(empty, fill, scores).mean()

    @staticmethod
    def _validate_top_k(top_k: Optional[int]) -> Optional[int]:
        """Shared ``top_k`` argument check for the @k subclasses."""
        if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
            raise ValueError("`top_k` has to be a positive integer or None")
        return top_k

    def _in_topk(self, valid: Array) -> Array:
        """Mask of slots inside this metric's top-k cut (all valid slots when unset)."""
        top_k = getattr(self, "top_k", None)
        if top_k is None:
            return valid
        return valid & (jnp.arange(valid.shape[-1]) < top_k)

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        """Batched per-query scores ``(num_queries,)`` over rank-sorted dense rows.

        Built-ins override this. The default bridges to the reference-style per-query
        ``_metric`` hook so user subclasses keep working, at python-loop cost.
        """
        scores = []
        for row in range(preds_mat.shape[0]):
            keep = valid[row]
            n = int(np.asarray(keep).sum())
            target_row = target_mat[row, :n]
            if not self.allow_non_binary_target:
                # the dense pack stores float32; hand binary metrics ints back so a
                # `_metric` delegating to the public functionals passes their checks
                target_row = target_row.astype(jnp.int32)
            scores.append(self._metric(preds_mat[row, :n], target_row))
        return jnp.stack([jnp.asarray(s, dtype=jnp.float32) for s in scores]) if scores else jnp.zeros((0,))

    def _metric(self, preds: Array, target: Array) -> Array:
        """Per-query metric over rank-sorted 1-D slices (reference ``base.py:152-158``)."""
        raise NotImplementedError

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


# re-exported for subclasses
__all__ = ["RetrievalMetric", "_pack_query_groups"]
