"""RetrievalRecall (reference ``retrieval/recall.py:27``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalRecall(RetrievalMetric):
    """Recall@k per query, averaged.

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> from torchmetrics_tpu.retrieval.recall import RetrievalRecall
        >>> metric = RetrievalRecall()
        >>> _ = metric.update(preds, target, indexes=indexes)
        >>> print(round(float(metric.compute()), 4))
        1.0
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        self.top_k = self._validate_top_k(top_k)

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        relevant = (target_mat * self._in_topk(valid)).sum(axis=-1)
        n_pos = (target_mat * valid).sum(axis=-1)
        return jnp.where(n_pos == 0, 0.0, relevant / jnp.where(n_pos == 0, 1.0, n_pos))
