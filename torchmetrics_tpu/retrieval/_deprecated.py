"""Deprecated-root-import shims (reference ``retrieval/_deprecated.py``)."""

from torchmetrics_tpu.retrieval import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRPrecision,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
)
from torchmetrics_tpu.utilities.deprecation import root_alias

_RetrievalFallOut = root_alias(RetrievalFallOut, "retrieval")
_RetrievalHitRate = root_alias(RetrievalHitRate, "retrieval")
_RetrievalMAP = root_alias(RetrievalMAP, "retrieval")
_RetrievalMRR = root_alias(RetrievalMRR, "retrieval")
_RetrievalNormalizedDCG = root_alias(RetrievalNormalizedDCG, "retrieval")
_RetrievalPrecision = root_alias(RetrievalPrecision, "retrieval")
_RetrievalPrecisionRecallCurve = root_alias(RetrievalPrecisionRecallCurve, "retrieval")
_RetrievalRPrecision = root_alias(RetrievalRPrecision, "retrieval")
_RetrievalRecall = root_alias(RetrievalRecall, "retrieval")
_RetrievalRecallAtFixedPrecision = root_alias(RetrievalRecallAtFixedPrecision, "retrieval")
