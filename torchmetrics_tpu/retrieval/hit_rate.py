"""RetrievalHitRate (reference ``retrieval/hit_rate.py:27``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalHitRate(RetrievalMetric):
    """Probability the top k contains at least one relevant document."""

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        self.top_k = self._validate_top_k(top_k)

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        return ((target_mat * self._in_topk(valid)).sum(axis=-1) > 0).astype(jnp.float32)
