"""RetrievalHitRate (reference ``retrieval/hit_rate.py:27``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalHitRate(RetrievalMetric):
    """Probability the top k contains at least one relevant document.

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> from torchmetrics_tpu.retrieval.hit_rate import RetrievalHitRate
        >>> metric = RetrievalHitRate()
        >>> _ = metric.update(preds, target, indexes=indexes)
        >>> print(round(float(metric.compute()), 4))
        1.0
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        self.top_k = self._validate_top_k(top_k)

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        return ((target_mat * self._in_topk(valid)).sum(axis=-1) > 0).astype(jnp.float32)
