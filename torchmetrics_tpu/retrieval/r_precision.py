"""RetrievalRPrecision (reference ``retrieval/r_precision.py:27``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalRPrecision(RetrievalMetric):
    """Precision at the R-th rank, R = per-query relevant count (branch-free mask form).

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> from torchmetrics_tpu.retrieval.r_precision import RetrievalRPrecision
        >>> metric = RetrievalRPrecision()
        >>> _ = metric.update(preds, target, indexes=indexes)
        >>> print(round(float(metric.compute()), 4))
        0.75
    """

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        ranks = jnp.arange(1, target_mat.shape[-1] + 1)
        n_rel = (target_mat * valid).sum(axis=-1, keepdims=True)
        in_first_r = (ranks <= n_rel) & valid
        hit = (target_mat * in_first_r).sum(axis=-1)
        n_rel = n_rel.squeeze(-1)
        return jnp.where(n_rel == 0, 0.0, hit / jnp.where(n_rel == 0, 1.0, n_rel))
