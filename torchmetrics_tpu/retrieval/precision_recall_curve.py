"""RetrievalPrecisionRecallCurve and RetrievalRecallAtFixedPrecision
(reference ``retrieval/precision_recall_curve.py:60,265``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.retrieval.base import RetrievalMetric, _pack_query_groups
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.plot import plot_curve

Array = jax.Array


def _retrieval_recall_at_fixed_precision(
    precision: Array, recall: Array, top_k: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Highest recall (and its k) among points with precision >= min_precision (reference ``:33-57``)."""
    p = np.asarray(precision)
    r = np.asarray(recall)
    k = np.asarray(top_k)
    candidates = [(rr, kk) for pp, rr, kk in zip(p, r, k) if pp >= min_precision]
    if candidates:
        max_recall, best_k = max(candidates)
    else:
        max_recall, best_k = 0.0, len(k)
    if max_recall == 0.0:
        best_k = len(k)
    return jnp.asarray(max_recall, dtype=jnp.float32), jnp.asarray(best_k, dtype=jnp.int32)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Averaged precision@k / recall@k curves over queries, k in [1, max_k]."""

    def __init__(
        self,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        self.max_k = self._validate_top_k(max_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.adaptive_k = adaptive_k

    def compute(self) -> Tuple[Array, Array, Array]:  # type: ignore[override]
        """Batched curves over the dense rank matrix (one XLA reduction per point set)."""
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        preds_mat, target_mat, valid = _pack_query_groups(indexes, preds, target)
        _, max_len = target_mat.shape
        max_k = self.max_k if self.max_k is not None else max_len

        positions = jnp.arange(max_k)
        # cumulative relevant count in the first k ranks, truncated to each row's docs
        padded_t = jnp.pad(target_mat * valid, ((0, 0), (0, max(0, max_k - max_len))))[:, :max_k]
        relevant = jnp.cumsum(padded_t, axis=-1)

        n_valid = valid.sum(axis=-1, keepdims=True)
        if self.adaptive_k:
            topk = jnp.minimum(positions + 1, n_valid).astype(jnp.float32)
        else:
            topk = jnp.broadcast_to((positions + 1).astype(jnp.float32), relevant.shape)

        n_pos = (target_mat * valid).sum(axis=-1, keepdims=True)
        recalls = jnp.where(n_pos == 0, 0.0, relevant / jnp.where(n_pos == 0, 1.0, n_pos))
        precisions = jnp.where(n_pos == 0, 0.0, relevant / topk)

        empty = n_pos.squeeze(-1) == 0
        if self.empty_target_action == "error" and bool(empty.any()):
            raise ValueError("`compute` method was provided with a query with no positive target.")
        if self.empty_target_action == "skip":
            keep = ~empty
            n_kept = int(np.asarray(keep).sum())
            if n_kept == 0:
                zero = jnp.zeros((max_k,))
                return zero, zero, jnp.arange(1, max_k + 1)
            precision = (precisions * keep[:, None]).sum(axis=0) / n_kept
            recall = (recalls * keep[:, None]).sum(axis=0) / n_kept
        else:
            fill = 1.0 if self.empty_target_action == "pos" else 0.0
            precision = jnp.where(empty[:, None], fill, precisions).mean(axis=0)
            recall = jnp.where(empty[:, None], fill, recalls).mean(axis=0)

        return precision, recall, jnp.arange(1, max_k + 1)

    def plot(self, curve: Optional[Tuple[Array, Array, Array]] = None, ax: Optional[Any] = None) -> Any:
        curve = curve or self.compute()
        return plot_curve(curve, ax=ax, label_names=("Recall", "Precision"), name=type(self).__name__)


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Max recall@k whose precision@k clears ``min_precision`` (reference ``:265-354``).

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> from torchmetrics_tpu.retrieval.precision_recall_curve import RetrievalRecallAtFixedPrecision
        >>> metric = RetrievalRecallAtFixedPrecision(min_precision=0.5)
        >>> _ = metric.update(preds, target, indexes=indexes)
        >>> print(tuple(round(float(v), 4) for v in metric.compute()))
        (1.0, 3.0)
    """

    def __init__(
        self,
        min_precision: float = 0.0,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            max_k=max_k, adaptive_k=adaptive_k, empty_target_action=empty_target_action,
            ignore_index=ignore_index, **kwargs,
        )
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a float between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        precision, recall, top_k = super().compute()
        return _retrieval_recall_at_fixed_precision(precision, recall, top_k, self.min_precision)
