"""RetrievalPrecision (reference ``retrieval/precision.py:27``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalPrecision(RetrievalMetric):
    """Precision@k per query, averaged (reference semantics incl. ``adaptive_k``).

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> from torchmetrics_tpu.retrieval.precision import RetrievalPrecision
        >>> metric = RetrievalPrecision()
        >>> _ = metric.update(preds, target, indexes=indexes)
        >>> print(round(float(metric.compute()), 4))
        0.4167
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        adaptive_k: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        self.top_k = self._validate_top_k(top_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.adaptive_k = adaptive_k

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        max_len = target_mat.shape[-1]
        positions = jnp.arange(max_len)
        n_valid = valid.sum(axis=-1)
        if self.top_k is None:
            k_den = n_valid.astype(jnp.float32)
            in_topk = valid
        else:
            if self.adaptive_k:
                # clamp per query to its own document count
                k_den = jnp.minimum(self.top_k, n_valid).astype(jnp.float32)
            else:
                k_den = jnp.full(n_valid.shape, float(self.top_k))
            in_topk = valid & (positions < self.top_k)
        relevant = (target_mat * in_topk).sum(axis=-1)
        return relevant / k_den
