"""RetrievalNormalizedDCG (reference ``retrieval/ndcg.py:27``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalNormalizedDCG(RetrievalMetric):
    """nDCG@k per query with graded relevance, batched over the dense rank matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> from torchmetrics_tpu.retrieval.ndcg import RetrievalNormalizedDCG
        >>> metric = RetrievalNormalizedDCG()
        >>> _ = metric.update(preds, target, indexes=indexes)
        >>> print(round(float(metric.compute()), 4))
        0.9599
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        self.top_k = self._validate_top_k(top_k)
        self.allow_non_binary_target = True

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        max_len = target_mat.shape[-1]
        k = min(self.top_k, max_len) if self.top_k is not None else max_len
        positions = jnp.arange(max_len)
        discount = 1.0 / jnp.log2(positions + 2.0)
        dcg = (target_mat * self._in_topk(valid) * discount).sum(axis=-1)
        ideal = -jnp.sort(-(target_mat * valid), axis=-1)
        idcg = (ideal * (positions < k) * discount).sum(axis=-1)
        return jnp.where(idcg == 0, 0.0, dcg / jnp.where(idcg == 0, 1.0, idcg))
