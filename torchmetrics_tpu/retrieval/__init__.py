"""Retrieval metrics (reference ``src/torchmetrics/retrieval/__init__.py``)."""

from torchmetrics_tpu.retrieval.average_precision import RetrievalMAP
from torchmetrics_tpu.retrieval.base import RetrievalMetric
from torchmetrics_tpu.retrieval.fall_out import RetrievalFallOut
from torchmetrics_tpu.retrieval.hit_rate import RetrievalHitRate
from torchmetrics_tpu.retrieval.ndcg import RetrievalNormalizedDCG
from torchmetrics_tpu.retrieval.precision import RetrievalPrecision
from torchmetrics_tpu.retrieval.precision_recall_curve import (
    RetrievalPrecisionRecallCurve,
    RetrievalRecallAtFixedPrecision,
)
from torchmetrics_tpu.retrieval.r_precision import RetrievalRPrecision
from torchmetrics_tpu.retrieval.recall import RetrievalRecall
from torchmetrics_tpu.retrieval.reciprocal_rank import RetrievalMRR

__all__ = [
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMetric",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRPrecision",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
]
