"""RetrievalMRR (reference ``retrieval/reciprocal_rank.py:27``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank: ``argmax`` over the rank-sorted relevance picks the first hit."""

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        rel = target_mat * valid
        first = jnp.argmax(rel > 0, axis=-1)
        hit_exists = rel.sum(axis=-1) > 0
        return jnp.where(hit_exists, 1.0 / (first + 1.0), 0.0)
