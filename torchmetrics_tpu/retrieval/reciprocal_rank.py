"""RetrievalMRR (reference ``retrieval/reciprocal_rank.py:27``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank: ``argmax`` over the rank-sorted relevance picks the first hit.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMRR
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> mrr = RetrievalMRR()
        >>> print(round(float(mrr(preds, target, indexes=indexes)), 4))
        0.75
    """

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        rel = target_mat * valid
        first = jnp.argmax(rel > 0, axis=-1)
        hit_exists = rel.sum(axis=-1) > 0
        return jnp.where(hit_exists, 1.0 / (first + 1.0), 0.0)
