"""RetrievalMAP (reference ``retrieval/average_precision.py:27``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean average precision over queries, batched over the dense rank matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMAP
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> rmap = RetrievalMAP()
        >>> print(round(float(rmap(preds, target, indexes=indexes)), 4))
        0.7917
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        self.top_k = self._validate_top_k(top_k)

    def _metric_dense(self, preds_mat: Array, target_mat: Array, valid: Array) -> Array:
        max_len = target_mat.shape[-1]
        positions = jnp.arange(max_len)
        rel = target_mat * self._in_topk(valid)
        j = jnp.cumsum(rel, axis=-1)
        ranks = positions + 1.0
        n_rel = rel.sum(axis=-1)
        ap = jnp.sum(rel * j / ranks, axis=-1) / jnp.where(n_rel == 0, 1.0, n_rel)
        return jnp.where(n_rel == 0, 0.0, ap)
