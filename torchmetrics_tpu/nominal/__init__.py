"""Nominal-association metrics (reference ``src/torchmetrics/nominal/__init__.py``)."""

from torchmetrics_tpu.nominal.cramers import CramersV
from torchmetrics_tpu.nominal.fleiss_kappa import FleissKappa
from torchmetrics_tpu.nominal.pearson import PearsonsContingencyCoefficient
from torchmetrics_tpu.nominal.theils_u import TheilsU
from torchmetrics_tpu.nominal.tschuprows import TschuprowsT

__all__ = [
    "CramersV",
    "FleissKappa",
    "PearsonsContingencyCoefficient",
    "TheilsU",
    "TschuprowsT",
]
