"""Modular PearsonsContingencyCoefficient (reference ``nominal/pearson.py``)."""

from __future__ import annotations

from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.nominal.pearson import (
    _pearsons_contingency_coefficient_compute,
    _pearsons_contingency_coefficient_update,
)
from torchmetrics_tpu.functional.nominal.utils import _nominal_input_validation
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class PearsonsContingencyCoefficient(Metric):
    """Pearson's contingency coefficient over a device table (reference ``pearson.py:28-136``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 0, 1])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 0, 0])
        >>> from torchmetrics_tpu.nominal.pearson import PearsonsContingencyCoefficient
        >>> metric = PearsonsContingencyCoefficient(num_classes=3)
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.6631
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    confmat: Array

    def __init__(
        self,
        num_classes: int,
        nan_strategy: str = "replace",
        nan_replace_value: Optional[Union[int, float]] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        _nominal_input_validation(nan_strategy, nan_replace_value)
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state("confmat", jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Fold a batch of label pairs into the table."""
        confmat = _pearsons_contingency_coefficient_update(
            preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value
        )
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        """Contingency coefficient over the accumulated table."""
        return _pearsons_contingency_coefficient_compute(self.confmat)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
