"""Modular FleissKappa (reference ``nominal/fleiss_kappa.py``)."""

from __future__ import annotations

from typing import Any, List

import jax

from torchmetrics_tpu.functional.nominal.fleiss_kappa import _fleiss_kappa_compute, _fleiss_kappa_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class FleissKappa(Metric):
    """Fleiss' kappa with a concatenated counts-matrix state (reference ``fleiss_kappa.py:27-120``).

    Example:
        >>> import jax.numpy as jnp
        >>> ratings = jnp.asarray([[2, 1, 0], [1, 1, 1], [0, 2, 1], [3, 0, 0]])
        >>> from torchmetrics_tpu.nominal.fleiss_kappa import FleissKappa
        >>> metric = FleissKappa(mode='counts')
        >>> _ = metric.update(ratings)
        >>> print(round(float(metric.compute()), 4))
        0.0455
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    counts: List[Array]

    def __init__(self, mode: str = "counts", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if mode not in ("counts", "probs"):
            raise ValueError("Argument ``mode`` must be one of ['counts', 'probs']")
        self.mode = mode
        self.add_state("counts", default=[], dist_reduce_fx="cat")

    def update(self, ratings: Array) -> None:
        """Buffer the per-sample category-count rows for one batch."""
        counts = _fleiss_kappa_update(ratings, self.mode)
        self.counts.append(counts)

    def compute(self) -> Array:
        """Kappa over all rated samples."""
        return _fleiss_kappa_compute(dim_zero_cat(self.counts))

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
