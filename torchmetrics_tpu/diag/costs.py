"""Cost & memory ledger — what every compiled executable actually costs.

The engines compile a metric's hot path into cached XLA executables; until now
the only evidence about those executables was *count* shaped (traces,
dispatches, cache hits). This module records what each executable **costs**,
populated once per compile from XLA's own analyses:

- the engines compile through :func:`aot_compile`, which replaces the lazy
  ``jax.jit`` dispatch path with the ahead-of-time chain
  ``jit(f).lower(*args).compile()`` — the SAME single trace+compile the lazy
  path would do (measured: identical per-dispatch cost, ~8 µs on CPU), but the
  :class:`jax.stages.Compiled` handle exposes ``cost_analysis()`` /
  ``memory_analysis()``;
- each compile lands one :class:`ExecutableCost` entry in a process-wide
  ledger keyed by ``(owner, kind, signature)``: flops, bytes accessed,
  argument/output/temp/generated-code bytes, a peak-bytes figure, the bytes the
  state donation saved, and the compile wall-time;
- backends that do not implement an analysis (``None`` / ``Unimplemented``)
  degrade to ``None``-valued fields, never to an error — the executable still
  runs.

The ledger is the "what does my epoch cost in silicon terms" half of the
observability story; :func:`state_footprint` adds the live "what does my
metric state hold in HBM right now" half, deduplicating buffers shared by
compute-group view members.

Everything here is cold-path: the ledger is touched only at compile time
(once per signature) and at report time. ``TORCHMETRICS_TPU_COSTS=0`` disables
the analysis collection entirely (compiles fall back to the plain ``jax.jit``
dispatch path).
"""

from __future__ import annotations

import os
import zlib
from time import perf_counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ExecutableCost",
    "aot_compile",
    "costs_enabled",
    "ledger_snapshot",
    "reset_ledger",
    "set_costs_enabled",
    "state_footprint",
]

#: env knob: "0" disables ledger collection (plain jit dispatch, no analyses)
COSTS_ENV_VAR = "TORCHMETRICS_TPU_COSTS"

_enabled_override: Optional[bool] = None


def costs_enabled() -> bool:
    """Whether engine compiles record ledger entries (default: on)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(COSTS_ENV_VAR, "").strip() != "0"


def set_costs_enabled(value: Optional[bool]) -> None:
    """Force the ledger on/off process-wide; ``None`` restores the env/default."""
    global _enabled_override
    _enabled_override = value


class ExecutableCost:
    """One compiled executable's cost/memory record (one per (owner, kind, signature))."""

    __slots__ = (
        "owner", "kind", "signature", "arg_leaves", "arg_bytes", "flops",
        "bytes_accessed", "peak_bytes", "argument_bytes", "output_bytes",
        "temp_bytes", "generated_code_bytes", "donation_savings_bytes",
        "compile_ms", "compiles", "cache_hits", "deserialize_ms",
        "time_to_first_dispatch_ms", "analyses_ok",
    )

    def __init__(self, owner: str, kind: str, signature: str) -> None:
        self.owner = owner
        self.kind = kind  # update | fused | sync-fold | sync-compute | compute
        self.signature = signature
        self.arg_leaves = 0
        self.arg_bytes = 0
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.peak_bytes: Optional[int] = None
        self.argument_bytes: Optional[int] = None
        self.output_bytes: Optional[int] = None
        self.temp_bytes: Optional[int] = None
        self.generated_code_bytes: Optional[int] = None
        self.donation_savings_bytes = 0
        self.compile_ms = 0.0  # wall-time summed over compiles (see `compiles` for the divisor)
        self.compiles = 0  # real lower+compile passes (re-compiles of a dropped entry accumulate)
        self.cache_hits = 0  # compiles served by deserializing a persisted executable
        self.deserialize_ms = 0.0  # wall-time summed over persistent-cache loads
        self.time_to_first_dispatch_ms: Optional[float] = None  # latest path to a ready executable: compile (cold) or deserialize (warm)
        self.analyses_ok = False

    def as_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}


# process-wide ledger: (owner, kind, signature) -> ExecutableCost. Insertion
# order is compile order; snapshots re-sort deterministically.
_LEDGER: "Dict[Tuple[str, str, str], ExecutableCost]" = {}


def _arg_signature(args: Sequence[Any]) -> Tuple[str, int, int]:
    """(digest, leaf_count, total_bytes) over the example args' shapes/dtypes."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    parts = []
    total = 0
    for leaf in leaves:
        parts.append(f"{getattr(leaf, 'dtype', type(leaf).__name__)}{list(getattr(leaf, 'shape', ()))}")
        total += int(getattr(leaf, "nbytes", 0))
    digest = format(zlib.crc32("|".join(parts).encode()) & 0xFFFFFFFF, "08x")
    return digest, len(leaves), total


def _harvest_cost(entry: ExecutableCost, compiled: Any) -> None:
    """Fill the XLA analysis fields, guarded per analysis (None on backends
    that do not implement one — the executable is unaffected)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            entry.flops = float(ca.get("flops", 0.0))
            entry.bytes_accessed = float(ca.get("bytes accessed", 0.0))
            entry.analyses_ok = True
    except Exception:  # noqa: BLE001 — analysis support is backend-dependent
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            entry.argument_bytes = int(getattr(ma, "argument_size_in_bytes", 0))
            entry.output_bytes = int(getattr(ma, "output_size_in_bytes", 0))
            entry.temp_bytes = int(getattr(ma, "temp_size_in_bytes", 0))
            entry.generated_code_bytes = int(getattr(ma, "generated_code_size_in_bytes", 0))
            peak = getattr(ma, "peak_memory_in_bytes", None)
            if peak is None:
                # backend reports no dedicated peak: the live-at-once upper
                # bound is arguments + outputs + temporaries + code
                peak = entry.argument_bytes + entry.output_bytes + entry.temp_bytes + entry.generated_code_bytes
            entry.peak_bytes = int(peak)
            entry.analyses_ok = True
    except Exception:  # noqa: BLE001
        pass


def aot_compile(
    fn: Any, owner: str, kind: str, args: Sequence[Any], donated_bytes: int = 0, stats: Any = None
) -> Any:
    """Compile ``fn`` (a ``jax.jit`` wrapper) ahead-of-time for ``args`` and
    record a ledger entry; returns the executable to dispatch with.

    With the persistent cache enabled (``TORCHMETRICS_TPU_PERSIST``, see
    ``engine/persist.py``), a matching persisted executable is deserialized
    instead — NO ``lower()``/``compile()`` at all, the artifact carries its
    own arg trees — and every fresh compile is serialized back for the next
    process. The persist key extends the arg-signature digest with the args'
    placement token, so two same-shape compiles pinned to different devices
    or shardings never collide on one artifact; hit/miss land on ``stats``
    (the owning :class:`~torchmetrics_tpu.engine.stats.EngineStats`) and on
    the ledger entry's ``cache_hits``/``deserialize_ms``/
    ``time_to_first_dispatch_ms``.

    Tracing/compile errors propagate unchanged — they are the caller's
    eligibility signal (the same exceptions the lazy first dispatch would
    raise). With the ledger disabled AND persistence off, ``fn`` is returned
    untouched and the lazy jit dispatch path applies.
    """
    from torchmetrics_tpu.engine import persist as _persist

    persist_on = _persist.persist_dir() is not None
    if not costs_enabled() and not persist_on:
        return fn
    digest, leaves, arg_bytes = _arg_signature(args)
    entry: Optional[ExecutableCost] = None
    if costs_enabled():
        entry = _LEDGER.get((owner, kind, digest))
        if entry is None:
            entry = ExecutableCost(owner, kind, digest)
            _LEDGER[(owner, kind, digest)] = entry
        entry.arg_leaves = leaves
        entry.arg_bytes = arg_bytes
        entry.donation_savings_bytes = int(donated_bytes)

    persist_sig = ""
    if persist_on:
        from torchmetrics_tpu.parallel.sharding import placement_token

        try:
            place = placement_token(list(args))
        except Exception:  # noqa: BLE001 — placement is a key refinement, never a gate
            place = ""
        persist_sig = f"{digest}/{place}"
        t0 = perf_counter()
        compiled = _persist.try_load_executable(owner, kind, persist_sig)
        if compiled is not None:
            deserialize_ms = (perf_counter() - t0) * 1e3
            if entry is not None:
                entry.cache_hits += 1
                entry.deserialize_ms += deserialize_ms
                entry.time_to_first_dispatch_ms = round(deserialize_ms, 3)
                _harvest_cost(entry, compiled)
            if stats is not None:
                stats.persist_hits += 1
            return compiled
        if stats is not None:
            stats.persist_misses += 1

    t0 = perf_counter()
    compiled = fn.lower(*args).compile()
    compile_ms = (perf_counter() - t0) * 1e3
    if entry is not None:
        entry.compiles += 1
        entry.compile_ms += compile_ms  # re-compiles of a dropped entry accumulate
        entry.time_to_first_dispatch_ms = round(compile_ms, 3)
        _harvest_cost(entry, compiled)
    if persist_on:
        _persist.store_executable(owner, kind, persist_sig, compiled)
    return compiled


# ------------------------------------------------------------------ reporting


def ledger_entries() -> List[Dict[str, Any]]:
    """Every recorded executable, deterministically sorted (owner, kind, signature)."""
    return [e.as_dict() for _, e in sorted(_LEDGER.items())]


def ledger_snapshot() -> Dict[str, Any]:
    """Aggregated ledger view::

        {
          "executables": [per-executable dicts, sorted],
          "totals": {"executables", "flops", "bytes_accessed", "peak_bytes_max",
                     "compile_ms", "compiles", "cache_hits", "deserialize_ms",
                     "donation_savings_bytes"},
          "per_owner": {owner: same totals over that owner's executables},
        }
    """
    entries = ledger_entries()

    def _totals(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
        return {
            "executables": len(rows),
            "flops": sum(r["flops"] or 0.0 for r in rows),
            "bytes_accessed": sum(r["bytes_accessed"] or 0.0 for r in rows),
            "peak_bytes_max": max((r["peak_bytes"] or 0 for r in rows), default=0),
            "compile_ms": round(sum(r["compile_ms"] for r in rows), 3),
            "compiles": sum(r["compiles"] for r in rows),
            "cache_hits": sum(r["cache_hits"] for r in rows),
            "deserialize_ms": round(sum(r["deserialize_ms"] for r in rows), 3),
            "donation_savings_bytes": sum(r["donation_savings_bytes"] for r in rows),
        }

    per_owner: Dict[str, List[Dict[str, Any]]] = {}
    for row in entries:
        per_owner.setdefault(row["owner"], []).append(row)
    return {
        "executables": entries,
        "totals": _totals(entries),
        "per_owner": {owner: _totals(rows) for owner, rows in sorted(per_owner.items())},
    }


def reset_ledger() -> None:
    """Drop every recorded executable cost (``reset_engine_stats`` calls this)."""
    _LEDGER.clear()


# ------------------------------------------------------------------ footprint


def _leaf_bytes(value: Any) -> Tuple[int, List[Tuple[int, int]]]:
    """(total nbytes, [(buffer id, nbytes)]) over an array or list-state value."""
    leaves = value if isinstance(value, list) else [value]
    total = 0
    buffers = []
    for leaf in leaves:
        n = int(getattr(leaf, "nbytes", 0))
        if n:
            total += n
            buffers.append((id(leaf), n))
    return total, buffers


def _leaf_device_bytes(value: Any) -> int:
    """Bytes ONE device holds for this value — the sharded-state footprint.

    Replicated/single-device leaves cost their full ``nbytes`` per device; a
    leaf partitioned by the SPMD layer (``parallel/sharding.py``) costs the
    largest addressable shard (~``nbytes / mesh``). Pure metadata reads — no
    host transfer, shard sizes come from the sharding layout.
    """
    total = 0
    for leaf in value if isinstance(value, list) else [value]:
        n = int(getattr(leaf, "nbytes", 0))
        if not n:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and not getattr(sharding, "is_fully_replicated", True):
            try:
                n = max(int(sh.data.nbytes) for sh in leaf.addressable_shards)
            except Exception:  # noqa: BLE001 — unreadable layout reads as replicated
                pass
        total += n
    return total


def _rider_values(metric: Any) -> list:
    """Live rider buffers a metric holds beyond its registered states.

    The sentinel bitmask, the quarantine counter, and the compensation
    residual dict are real HBM the footprint must not under-report.
    """
    values = []
    sentinel = getattr(metric, "_sentinel_flags", None)
    if sentinel is not None:
        values.append(sentinel)
    quarantine = metric.__dict__.get("_quarantined_count")
    if quarantine is not None:
        values.append(quarantine)
    residuals = metric.__dict__.get("_comp_residuals")
    if residuals:
        values.extend(residuals.values())
    return values


def state_footprint(obj: Any) -> Dict[str, Any]:
    """Live state-memory footprint of a Metric or MetricCollection.

    For a single metric: per-state and total bytes of the registered states
    (list states sum their elements) plus any live rider buffers (sentinel
    bitmask, quarantine counter, compensation residuals). For a collection:
    per-member nominal bytes plus ``unique_bytes`` — the deduplicated total,
    counting each underlying buffer once (compute-group view members SHARE
    their owner's arrays, so nominal sums over-count what HBM actually holds)
    — and a ``groups`` section reporting each multi-member compute group's
    canonical state EXACTLY ONCE (the CSE accounting: an N-member fused
    family holds ~1/N of the unfused sum).

    The walk is side-effect free: for a discovered compute group, view
    members' REGISTERED states are read from the group OWNER (the canonical
    buffers a view anchors to at its next materialization) instead of
    mutating the collection by materializing views — a collection whose views
    have not been re-anchored yet (construction-time CSE groups before the
    first accessor, a donated drain that has not propagated) would otherwise
    count every view's stale private buffers as unique. Rider buffers
    (sentinel, quarantine counter, residuals) are genuinely per-member and
    read from the member itself.
    """
    if hasattr(obj, "_defaults"):  # duck-typed Metric
        per_state = {}
        total = 0
        per_device = 0
        for attr in obj._defaults:
            value = getattr(obj, attr)
            n, _ = _leaf_bytes(value)
            per_state[attr] = n
            total += n
            per_device += _leaf_device_bytes(value)
        for value in _rider_values(obj):
            n, _ = _leaf_bytes(value)
            # the sentinel key predates the rider split; keep its entry name
            key = "_sentinel_flags" if value is getattr(obj, "_sentinel_flags", None) else "_riders"
            per_state[key] = per_state.get(key, 0) + n
            total += n
            per_device += _leaf_device_bytes(value)
        # per_device_bytes == total_bytes for replicated metrics; a class-axis
        # sharded state drops it to ~1/mesh — the driver-verifiable evidence
        # that sharded state actually costs 1/N of a device's HBM
        return {
            "owner": type(obj).__name__,
            "total_bytes": total,
            "per_device_bytes": per_device,
            "per_state": per_state,
        }
    if hasattr(obj, "_modules"):  # duck-typed MetricCollection
        owner_of: Dict[str, str] = {}
        if getattr(obj, "_groups_checked", False):
            for group in (getattr(obj, "_groups", None) or {}).values():
                names = list(getattr(group, "names", ()))
                for view_name in names[1:]:
                    owner_of[view_name] = names[0]
        per_metric = {}
        seen: set = set()
        unique = 0
        nominal = 0
        member_unique: Dict[str, int] = {}
        per_device = 0
        seen_device: set = set()
        for name, metric in obj._modules.items():
            m_total = 0
            m_unique = 0
            # a view member's registered states are (or will anchor to) the
            # owner's canonical buffers — read those, mutate nothing
            source = obj._modules.get(owner_of.get(name, name), metric)
            values = [getattr(source, attr) for attr in source._defaults]
            values.extend(_rider_values(metric))
            for value in values:
                total, buffers = _leaf_bytes(value)
                m_total += total
                # unique accounting: count each buffer id once across members
                for buf_id, nbytes in buffers:
                    if buf_id not in seen:
                        seen.add(buf_id)
                        unique += nbytes
                        m_unique += nbytes
                # per-device accounting, same dedupe: a sharded buffer costs
                # one shard per device however many views share it
                for leaf in value if isinstance(value, list) else [value]:
                    if getattr(leaf, "nbytes", 0) and id(leaf) not in seen_device:
                        seen_device.add(id(leaf))
                        per_device += _leaf_device_bytes(leaf)
            per_metric[name] = m_total
            member_unique[name] = m_unique
            nominal += m_total
        groups = []
        for group in (getattr(obj, "_groups", None) or {}).values():
            names = list(getattr(group, "names", ()))
            if len(names) < 2:
                continue
            # the group's unique bytes across ALL members: the canonical
            # state counted exactly once however many views share it (and
            # whichever member happened to walk first and claim the buffers)
            groups.append(
                {
                    "owner": group.owner,
                    "members": len(names),
                    "canonical_bytes": sum(member_unique.get(n, 0) for n in names),
                }
            )
        out = {
            "owner": type(obj).__name__,
            "total_bytes": nominal,
            "unique_bytes": unique,
            "shared_bytes": nominal - unique,
            # deduplicated one-device view of unique_bytes: sharded buffers
            # cost their largest addressable shard (~1/mesh), replicated ones
            # their full nbytes — mirrors the Metric branch's field
            "per_device_bytes": per_device,
            "per_metric": per_metric,
        }
        if groups:
            out["groups"] = groups
        return out
    raise TypeError(f"state_footprint expects a Metric or MetricCollection, got {type(obj).__name__}")
