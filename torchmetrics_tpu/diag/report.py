"""Diag reporting — aggregate events + engine counters; JSON / chrome-trace export.

Three consumers, one data path:

- :func:`diag_report` merges the process-wide engine counters
  (:func:`~torchmetrics_tpu.engine.stats.engine_report`) with the flight
  recorder's event stream into one per-metric timing/counter report — the
  "what did my epoch actually cost" dict.
- :func:`export_json` dumps the raw event stream (for offline diffing and the
  counter-regression tooling).
- :func:`export_chrome_trace` writes the events in the Chrome Trace Event
  format (``{"traceEvents": [...]}``), loadable in Perfetto
  (https://ui.perfetto.dev) — dispatch/step events with a measured
  ``dispatch_us`` become duration ("X")
  slices on a per-owner track; everything else becomes an instant ("i")
  marker. Durations are HOST-side spans (async launch + Python bookkeeping);
  device kernel time belongs to sampled ``device_us`` probes and to native
  ``jax.profiler`` traces, which these markers are designed to sit alongside.
  Multi-rank streams merge into one trace via
  :func:`torchmetrics_tpu.diag.timeline.merge_timelines`.
"""

from __future__ import annotations

import json
from collections import Counter, defaultdict
from typing import Any, Dict, List, Optional

from torchmetrics_tpu.diag.trace import FlightRecorder, TraceEvent, active_recorder

__all__ = ["diag_report", "export_chrome_trace", "export_json"]

# kinds whose events carry dispatch_us and render as duration slices.
# update.scan is ONE drained scan (its args carry the steps folded): the
# chrome trace renders one X-slice per drain, never K phantom per-step slices
_SPAN_KINDS = frozenset(
    {"update.dispatch", "fused.dispatch", "compute.dispatch", "collection.step", "sync.exchange", "update.scan"}
)


def _events_of(recorder: Optional[FlightRecorder]) -> List[TraceEvent]:
    rec = recorder if recorder is not None else active_recorder()
    return rec.snapshot() if rec is not None else []


def diag_report(recorder: Optional[FlightRecorder] = None, reset: bool = False) -> Dict[str, Any]:
    """One merged observability dict: engine counters + event aggregation.

    Returns::

        {
          "counters": engine_report(),          # process-wide EngineStats sums
          "events": {kind: count},              # exact, drop-proof
          "dropped": int,                       # ring-buffer overflow count
          "per_metric": {owner: {"dispatches", "dispatch_us", "device_us",
                                 "probes", "traces", "retraces",
                                 "fallbacks"}},
          "retraces": [{"owner", "kind", "cause"}],   # every recorded retrace
          "host_transfers": int,                # transfer.host + transfer.blocked
          "collective_bytes": int,              # bytes through sanctioned collectives
          "ledger": {...},                      # cost/memory ledger totals (diag/costs.py)
          "sentinels": [...],                   # per-metric health bitmasks (diag/sentinel.py)
          "histograms": [...],                  # latency/size distributions (diag/hist.py):
                                                # per (owner, kind, series) p50/p90/p99
          "profile": {...},                     # sampled-probe accounting (diag/profile.py)
        }

    Naming: ``dispatch_us`` is HOST wall-time around the **async** dispatch —
    the launch cost, NOT device time (the ``host_us``/``dur_us`` aliases from
    the profiling PR completed their one-release retention and are gone).
    True completion latency lives in ``device_us``,
    populated only by sampled profiling probes (``profile_context`` /
    ``TORCHMETRICS_TPU_PROFILE``).

    Dict sections are deterministically sorted so two reports of the same
    state serialize byte-identically (the counter gate diffs JSON exports).

    ``reset=True`` clears every surface this report covers afterwards — the
    engine counters, THIS report's recorder (the explicitly passed one, or the
    active one when none is passed; never an unrelated recorder that merely
    happens to be active), the cost ledger, the sentinel registry, the
    histograms, and the probe accounting — so a later report never attributes
    this run's compiles or flags to the next.
    """
    from torchmetrics_tpu.engine.stats import engine_report, reset_engine_counters

    rec = recorder if recorder is not None else active_recorder()
    events = rec.snapshot() if rec is not None else []
    counts: Counter = Counter(rec.counts) if rec is not None else Counter()

    per_metric: Dict[str, Dict[str, Any]] = defaultdict(
        lambda: {
            "dispatches": 0, "dispatch_us": 0.0, "device_us": 0.0, "probes": 0,
            "traces": 0, "retraces": 0, "fallbacks": 0,
            "scan_dispatches": 0, "scan_steps_folded": 0,
        }
    )
    retraces: List[Dict[str, Any]] = []
    collective_bytes = 0
    for ev in events:
        slot = per_metric[ev.owner or "<process>"]
        if ev.kind == "update.scan":
            # one drained scan = one dispatch folding `steps` updates; the
            # per-owner amortization factor derives below
            slot["dispatches"] += 1
            slot["dispatch_us"] += float(ev.data.get("dispatch_us", 0.0))
            slot["scan_dispatches"] += 1
            slot["scan_steps_folded"] += int(ev.data.get("steps", 0))
        elif ev.kind in _SPAN_KINDS:
            slot["dispatches"] += 1
            slot["dispatch_us"] += float(ev.data.get("dispatch_us", 0.0))
        elif ev.kind.endswith(".probe"):
            slot["probes"] += 1
            slot["device_us"] += float(ev.data.get("device_us", 0.0))
        elif ev.kind.endswith(".trace"):
            slot["traces"] += 1
        elif ev.kind.endswith(".retrace") or ev.kind.endswith("fold_retrace"):
            slot["retraces"] += 1
            retraces.append({"owner": ev.owner, "kind": ev.kind, "cause": ev.data.get("cause", "")})
        elif ev.kind == "fallback":
            slot["fallbacks"] += 1
        elif ev.kind == "collective":
            collective_bytes += int(ev.data.get("bytes", 0))
    from torchmetrics_tpu.diag.costs import ledger_snapshot
    from torchmetrics_tpu.diag.hist import histograms_snapshot
    from torchmetrics_tpu.diag.lineage import lineage_snapshot
    from torchmetrics_tpu.diag.profile import profile_snapshot
    from torchmetrics_tpu.diag.sentinel import sentinel_report

    for slot in per_metric.values():
        # dispatch-amortization factor: real steps folded per scan dispatch
        # (1.0 would be the unqueued engine; the K-fold win reads directly)
        slot["scan_amortization"] = (
            round(slot["scan_steps_folded"] / slot["scan_dispatches"], 2)
            if slot["scan_dispatches"]
            else 0.0
        )

    out: Dict[str, Any] = {
        "counters": engine_report(),
        "events": {k: counts[k] for k in sorted(counts)},
        "dropped": rec.dropped if rec is not None else 0,
        "per_metric": {k: dict(per_metric[k]) for k in sorted(per_metric)},
        "retraces": retraces,
        "host_transfers": counts.get("transfer.host", 0) + counts.get("transfer.blocked", 0),
        "collective_bytes": collective_bytes,
        "ledger": ledger_snapshot()["totals"],
        "sentinels": sentinel_report(),
        "histograms": histograms_snapshot(),
        "profile": profile_snapshot(),
        "provenance": lineage_snapshot(),
    }
    if reset:
        from torchmetrics_tpu.diag.costs import reset_ledger
        from torchmetrics_tpu.diag.hist import reset_histograms
        from torchmetrics_tpu.diag.lineage import reset_lineage
        from torchmetrics_tpu.diag.profile import reset_profile
        from torchmetrics_tpu.diag.sentinel import reset_sentinels

        reset_engine_counters()
        if rec is not None:
            rec.clear()
        reset_ledger()
        reset_sentinels()
        reset_histograms()
        reset_profile()
        # lockstep with reset_engine_stats: a stale watermark would attribute
        # the previous run's backlog to the fresh one as phantom staleness
        reset_lineage()
    return out


def export_json(path: str, recorder: Optional[FlightRecorder] = None) -> int:
    """Write the raw event stream as a JSON list; returns the event count."""
    events = _events_of(recorder)
    payload = [
        {"seq": ev.seq, "ts_us": round(ev.ts * 1e6, 3), "kind": ev.kind, "owner": ev.owner, **ev.data}
        for ev in events
    ]
    with open(path, "w") as fh:
        json.dump(payload, fh, default=str)
    return len(payload)


def export_chrome_trace(path: str, recorder: Optional[FlightRecorder] = None) -> int:
    """Write the events as a Perfetto-loadable chrome trace; returns the count.

    Layout: one process (pid 0, "torchmetrics_tpu"), one thread track per event
    owner. Events with a measured ``dispatch_us``
    become complete ("X") slices ending at their record timestamp; the rest
    are thread-scoped instants.
    Packed-sync ``collective`` events get a dedicated per-role track
    (``collective:reduce:int32``, ``collective:meta``, …) with their byte
    counts in ``args``, so sync cost sits visually next to compute cost
    instead of vanishing into the anonymous process track.
    """
    events = _events_of(recorder)
    tids: Dict[str, int] = {}
    trace_events: List[Dict[str, Any]] = [
        {"ph": "M", "pid": 0, "name": "process_name", "args": {"name": "torchmetrics_tpu"}}
    ]
    for ev in events:
        if ev.kind == "collective":
            owner = "collective:" + str(ev.data.get("label") or "?")
        else:
            owner = ev.owner or "<process>"
        tid = tids.setdefault(owner, len(tids) + 1)
        ts_us = ev.ts * 1e6
        dur = float(ev.data.get("dispatch_us", 0.0))
        entry: Dict[str, Any] = {
            "name": ev.kind,
            "pid": 0,
            "tid": tid,
            "args": {k: (v if isinstance(v, (int, float, bool, str)) else str(v)) for k, v in ev.data.items()},
        }
        if ev.kind in _SPAN_KINDS and dur > 0.0:
            # recorded AFTER the span: the slice ends at ev.ts
            entry.update(ph="X", ts=round(ts_us - dur, 3), dur=round(dur, 3))
        else:
            entry.update(ph="i", ts=round(ts_us, 3), s="t")
        trace_events.append(entry)
    for owner, tid in tids.items():
        trace_events.append(
            {"ph": "M", "pid": 0, "tid": tid, "name": "thread_name", "args": {"name": owner}}
        )
    with open(path, "w") as fh:
        json.dump({"traceEvents": trace_events, "displayTimeUnit": "ms"}, fh)
    return len(events)
