"""Declarative SLO engine — rolling-window objectives over the evidence plane.

The observability stack so far records *facts*: counters (``engine/stats.py``),
latency distributions (``diag/hist.py``), events (``diag/trace.py``). This
module adds *judgement*: a declarative registry of Service Level Objectives
(:data:`SLO_REGISTRY`) binding each objective to an existing histogram series
or counter field, evaluated over rolling windows with a fast/slow burn-rate
pair. The adaptive controller the roadmap specifies ("observe the PR-5
histograms and adjust knobs against an SLO target") consumes exactly this
surface, and the serving sidecar's ``/healthz`` readiness gate
(``serve/sidecar.py``) is its first consumer.

Spec anatomy (one :data:`SLO_REGISTRY` entry, pure literals so the static
analyzer can evaluate the table from source — tmlint rules TM801–TM803):

- ``signal`` — a histogram series name (``diag/telemetry.py`` ``_HIST_SERIES``
  key, e.g. ``sync_us``) or an :class:`~torchmetrics_tpu.engine.stats.
  EngineStats` counter field (e.g. ``sync_degraded_folds``). TM803 rejects a
  spec bound to a signal that does not exist — an SLO over a ghost signal
  would silently never breach.
- ``kind`` — ``quantile`` (windowed quantile of a histogram series vs a
  threshold, needs ``q``), ``rate`` (counter delta over the window vs a
  threshold), or ``ratio`` (counter delta divided by a ``denominator``
  counter's delta vs a threshold; an idle window — zero denominator — is
  compliant, not a division error).
- ``threshold`` — the objective bound; a measurement strictly above it
  violates. ``threshold: 0.0`` with ``kind: rate`` means "this counter must
  not move at all inside the window".
- ``blocking`` — whether a breach flips ``/healthz`` readiness to 503
  (``True``) or only raises the alerting surface — events, the
  ``tm_tpu_slo_breaches_total`` counter, per-SLO compliance gauges
  (``False``).

Burn-rate semantics (the fast/slow window pair, default slow window 300 s,
fast = slow / 10): a spec transitions to *breach* only when BOTH windows
violate — the slow window proves the problem is sustained, the fast window
proves it is still happening. It transitions back to *healthy* as soon as the
FAST window clears — recovery should be observed at the fast horizon, not
delayed by the slow window draining. With fewer samples than a full window,
the windows clip to the recorded history, so a cold engine's first violating
evaluation can breach — an SLO engine that stays green for its first five
minutes regardless of input would be worse than none.

Transitions are evidence, not just state: each one records a ``slo.breach`` /
``slo.recover`` flight-recorder event and bumps the ``slo_breaches`` /
``slo_recoveries`` counters; every pass bumps ``slo_evaluations``. The same
specs evaluate identically per-pod (default: the local registries) and
fleet-wide (``serve/fleet.py`` passes the merged histograms + summed counters
as explicit ``inputs``) — one objective language for one pod or forty.

Env knob (fail-loud per the PR-7 contract): ``TORCHMETRICS_TPU_SLO`` — unset
uses the 300 s default slow window; a positive number overrides it (seconds);
``0`` / ``off`` disables SLO evaluation (``/healthz`` skips the SLO gate);
anything else raises :class:`~torchmetrics_tpu.utilities.exceptions.
TorchMetricsUserError`. Tests and the bench use :func:`slo_context` instead of
mutating the environment.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from time import monotonic
from typing import Any, Deque, Dict, Generator, List, Optional, Tuple

from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.diag.hist import BOUNDS, Histogram
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

__all__ = [
    "SLO_REGISTRY",
    "SLOSpec",
    "SLOEngine",
    "blocking_breaches",
    "evaluate_slos",
    "reset_slo",
    "slo_context",
    "slo_enabled",
    "slo_state",
]

#: Default slow burn window (seconds); the fast window is slow / 10.
DEFAULT_SLOW_WINDOW_S = 300.0

#: The declarative SLO table — every objective the package evaluates, as pure
#: literals so ``tools/tmlint`` can evaluate it from source. Three-touch
#: registered like ``KNOB_REGISTRY``: declared here, bound to a real signal
#: (TM803), and documented as a ``slo:<id>`` token in
#: ``docs/pages/observability.md`` (TM801/TM802).
SLO_REGISTRY = {
    # fleet-wide p99 packed-sync latency objective: the paper's serving bound.
    # sync_us is recorded in microseconds; 5000 µs = 5 ms.
    "sync-latency-p99": {
        "signal": "sync_us",
        "kind": "quantile",
        "q": 0.99,
        "threshold": 5000.0,
        "blocking": False,
    },
    # degraded packed syncs mean a rank/pod dropped out of the membership —
    # any movement inside the window is a readiness problem, not a trend
    "sync-degraded-folds": {
        "signal": "sync_degraded_folds",
        "kind": "rate",
        "threshold": 0.0,
        "blocking": True,
    },
    # poisoned-batch quarantines per compiled dispatch — a trickle is the
    # mechanism working; a ratio above 1e-3 means the input pipeline is sick
    "quarantine-ratio": {
        "signal": "quarantined_batches",
        "kind": "ratio",
        "denominator": "dispatches",
        "threshold": 1e-3,
        "blocking": False,
    },
    # fleet staleness bound: pods excluded from a telemetry pull/merge round
    # (fault, stale watermark) — any exclusion flips fleet readiness
    "fleet-degraded-pulls": {
        "signal": "fleet_degraded_pulls",
        "kind": "rate",
        "threshold": 0.0,
        "blocking": True,
    },
    # value-freshness objective (diag/lineage.py): p99 steps-behind at
    # observation time. A pod whose observed values trail their enqueue
    # watermark by more than 32 steps is serving stale answers — blocking, so
    # /healthz drains it (naming the stale owner) until the fold catches up
    "value-freshness": {
        "signal": "staleness_steps",
        "kind": "quantile",
        "q": 0.99,
        "threshold": 32.0,
        "blocking": True,
    },
    # wall-clock companion bound: p99 age of the oldest unfolded enqueue at
    # observation time, in µs (5e6 = 5 s). Advisory — step-lag is the
    # authoritative freshness signal; this catches a stalled drain thread
    # whose step-lag is small but old
    "value-staleness-wall": {
        "signal": "staleness_us",
        "kind": "quantile",
        "q": 0.99,
        "threshold": 5000000.0,
        "blocking": False,
    },
}

_KINDS = ("quantile", "rate", "ratio")


@dataclass(frozen=True)
class SLOSpec:
    """One validated objective (the runtime form of a registry row)."""

    id: str
    signal: str
    kind: str
    threshold: float
    q: Optional[float] = None
    denominator: Optional[str] = None
    blocking: bool = False

    @staticmethod
    def from_registry(slo_id: str, row: Dict[str, Any]) -> "SLOSpec":
        kind = row["kind"]
        if kind not in _KINDS:
            raise TorchMetricsUserError(
                f"SLO {slo_id!r} has unknown kind {kind!r}; expected one of {_KINDS}."
            )
        if kind == "quantile" and not (0.0 < float(row.get("q", 0.0)) <= 1.0):
            raise TorchMetricsUserError(
                f"SLO {slo_id!r} is a quantile objective and needs 0 < q <= 1."
            )
        if kind == "ratio" and not row.get("denominator"):
            raise TorchMetricsUserError(
                f"SLO {slo_id!r} is a ratio objective and needs a denominator counter."
            )
        return SLOSpec(
            id=slo_id,
            signal=row["signal"],
            kind=kind,
            threshold=float(row["threshold"]),
            q=float(row["q"]) if "q" in row else None,
            denominator=row.get("denominator"),
            blocking=bool(row.get("blocking", False)),
        )


def _specs() -> Tuple[SLOSpec, ...]:
    return tuple(SLOSpec.from_registry(k, SLO_REGISTRY[k]) for k in sorted(SLO_REGISTRY))


# ------------------------------------------------------------------ env knob

_SLO_ENV_VAR = "TORCHMETRICS_TPU_SLO"

# context override installed by slo_context(): (slow_s, fast_s) or None
_window_override: Optional[Tuple[float, float]] = None


def _env_slo() -> Optional[float]:
    """The ONE recognized parser for ``TORCHMETRICS_TPU_SLO`` (fail-loud).

    Returns the slow-window seconds, or ``None`` when SLO evaluation is
    disabled (``0`` / ``off``).
    """
    raw = os.environ.get(_SLO_ENV_VAR)
    if raw is None:
        return DEFAULT_SLOW_WINDOW_S
    text = raw.strip().lower()
    if text in ("0", "off"):
        return None
    try:
        value = float(text)
    except ValueError:
        value = -1.0
    if value <= 0.0:
        raise TorchMetricsUserError(
            f"Invalid {_SLO_ENV_VAR}={raw!r}: expected a positive slow-window"
            " duration in seconds, or '0'/'off' to disable SLO evaluation."
            " Unset the variable to use the default"
            f" ({DEFAULT_SLOW_WINDOW_S:.0f} s)."
        )
    return value


def slo_enabled() -> bool:
    """Whether SLO evaluation is on (a :func:`slo_context` override wins)."""
    if _window_override is not None:
        return True
    return _env_slo() is not None


def _windows() -> Tuple[float, float]:
    """Active ``(slow_s, fast_s)`` pair (assumes :func:`slo_enabled`)."""
    if _window_override is not None:
        return _window_override
    slow = _env_slo()
    slow = DEFAULT_SLOW_WINDOW_S if slow is None else slow
    return slow, slow / 10.0


@contextmanager
def slo_context(slow_s: float, fast_s: Optional[float] = None) -> Generator:
    """Scoped window override (tests/bench — no environment mutation)."""
    global _window_override
    if slow_s <= 0.0:
        raise TorchMetricsUserError(f"slo_context needs slow_s > 0, got {slow_s!r}")
    prev = _window_override
    _window_override = (float(slow_s), float(fast_s) if fast_s else float(slow_s) / 10.0)
    try:
        yield
    finally:
        _window_override = prev


# ------------------------------------------------------------------ engine

def _merged_series(series: str) -> Histogram:
    """The local process's histogram for ``series``, merged across owners."""
    from torchmetrics_tpu.diag.hist import histogram_items, merge_hists

    out = Histogram()
    for (_owner, _kind, name), hist in histogram_items():
        if name == series:
            out = merge_hists(out, hist)
    return out


def _local_inputs() -> Dict[str, Any]:
    from torchmetrics_tpu.engine.stats import _COUNTER_FIELDS, engine_report

    report = engine_report()
    counters = {f: int(report.get(f, 0)) for f in _COUNTER_FIELDS}
    return {"counters": counters, "series": _merged_series}


class SLOEngine:
    """Rolling-window evaluator over one input surface (pod or fleet).

    One instance holds the per-spec sample windows and compliance state; the
    module-level singleton evaluates the local process, and
    ``serve/fleet.py`` owns a second instance fed with merged fleet inputs —
    same specs, same semantics, different measurement surface.
    """

    def __init__(self, owner: str = "slo") -> None:
        from torchmetrics_tpu.engine.stats import EngineStats

        self.owner = owner
        self.stats = EngineStats(owner)
        self._lock = threading.Lock()
        # spec id -> deque of (ts, snapshot); snapshot is a counts list for
        # quantile specs (monotone — window delta = elementwise subtraction)
        # or a (num, denom) counter pair for rate/ratio specs
        self._samples: Dict[str, Deque[Tuple[float, Any]]] = {}
        self._breaching: Dict[str, bool] = {}
        self._last: Dict[str, Optional[float]] = {}

    # -- window measurement ------------------------------------------------

    @staticmethod
    def _window_floor(window: Deque[Tuple[float, Any]], now: float, span: float):
        """Newest sample at or before ``now - span`` (window baseline); clips
        to the oldest recorded sample when history is shorter than the span."""
        floor = window[0]
        for ts, snap in window:
            if ts <= now - span:
                floor = (ts, snap)
            else:
                break
        return floor

    def _measure(self, spec: SLOSpec, window, now: float, span: float) -> Optional[float]:
        """The windowed measurement, or None when the window has no signal."""
        _, oldest = self._window_floor(window, now, span)
        _, newest = window[-1]
        if spec.kind == "quantile":
            delta = Histogram()
            delta.counts = [n - o for n, o in zip(newest, oldest)]
            delta.total = sum(delta.counts)
            if delta.total <= 0:
                return None
            # per-sample min/max are not recoverable from a counts delta; an
            # overflow-bucket rank resolves to the top boundary — finite, and
            # "at least this large" violates any realistic threshold
            delta.sum = 0.0
            delta.max = BOUNDS[-1]
            q = delta.quantile(spec.q if spec.q is not None else 0.99)
            return None if q is None else float(q)
        num_new, den_new = newest
        num_old, den_old = oldest
        moved = float(num_new - num_old)
        if spec.kind == "rate":
            return moved
        denom = float(den_new - den_old)
        if denom <= 0.0:
            return None  # idle window: compliant by definition
        return moved / denom

    # -- evaluation --------------------------------------------------------

    def evaluate(
        self, inputs: Optional[Dict[str, Any]] = None, now: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """Evaluate every registered spec once; returns the per-spec rows.

        ``inputs`` defaults to the local process (live histogram registry +
        ``engine_report`` counters); the fleet plane passes merged inputs.
        ``now`` is injectable so tests drive window time explicitly.
        """
        if not slo_enabled():
            return []
        if inputs is None:
            inputs = _local_inputs()
        counters: Dict[str, int] = inputs.get("counters", {})
        series_fn = inputs.get("series") or (lambda name: Histogram())
        ts = monotonic() if now is None else float(now)
        slow_s, fast_s = _windows()
        rows: List[Dict[str, Any]] = []
        with self._lock:
            self.stats.slo_evaluations += 1
            for spec in _specs():
                if spec.kind == "quantile":
                    snap: Any = list(series_fn(spec.signal).counts)
                else:
                    snap = (
                        int(counters.get(spec.signal, 0)),
                        int(counters.get(spec.denominator, 0)) if spec.denominator else 0,
                    )
                window = self._samples.setdefault(spec.id, deque())
                window.append((ts, snap))
                while len(window) > 2 and window[1][0] <= ts - slow_s:
                    window.popleft()
                fast = self._measure(spec, window, ts, fast_s)
                slow = self._measure(spec, window, ts, slow_s)
                fast_violates = fast is not None and fast > spec.threshold
                slow_violates = slow is not None and slow > spec.threshold
                was = self._breaching.get(spec.id, False)
                # breach needs BOTH burn windows; recovery follows the FAST one
                breaching = (fast_violates and slow_violates) if not was else fast_violates
                if breaching and not was:
                    self.stats.slo_breaches += 1
                    _diag.record(
                        "slo.breach", spec.id, signal=spec.signal,
                        measured=fast, threshold=spec.threshold, blocking=spec.blocking,
                    )
                elif was and not breaching:
                    self.stats.slo_recoveries += 1
                    _diag.record(
                        "slo.recover", spec.id, signal=spec.signal,
                        measured=fast, threshold=spec.threshold, blocking=spec.blocking,
                    )
                self._breaching[spec.id] = breaching
                self._last[spec.id] = fast if fast is not None else slow
                rows.append({
                    "id": spec.id,
                    "signal": spec.signal,
                    "kind": spec.kind,
                    "threshold": spec.threshold,
                    "blocking": spec.blocking,
                    "measured": self._last[spec.id],
                    "fast_violates": fast_violates,
                    "slow_violates": slow_violates,
                    "breaching": breaching,
                })
        return rows

    def state(self) -> List[Dict[str, Any]]:
        """Last-known per-spec compliance rows (no re-evaluation)."""
        with self._lock:
            return [
                {
                    "id": spec.id,
                    "signal": spec.signal,
                    "kind": spec.kind,
                    "threshold": spec.threshold,
                    "blocking": spec.blocking,
                    "measured": self._last.get(spec.id),
                    "breaching": self._breaching.get(spec.id, False),
                }
                for spec in _specs()
            ]

    def blocking_breaches(self) -> List[str]:
        """Ids of blocking specs currently in breach (readiness gate input)."""
        with self._lock:
            blocking = {s.id for s in _specs() if s.blocking}
            return sorted(sid for sid, b in self._breaching.items() if b and sid in blocking)

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._breaching.clear()
            self._last.clear()


# lazy module-level singleton: the local-process evaluator
_ENGINE: Optional[SLOEngine] = None
_ENGINE_LOCK = threading.Lock()


def _engine() -> SLOEngine:
    global _ENGINE
    if _ENGINE is None:
        with _ENGINE_LOCK:
            if _ENGINE is None:
                _ENGINE = SLOEngine("slo")
    return _ENGINE


def evaluate_slos(
    inputs: Optional[Dict[str, Any]] = None, now: Optional[float] = None
) -> List[Dict[str, Any]]:
    """Evaluate every SLO on the local singleton (see :meth:`SLOEngine.evaluate`)."""
    return _engine().evaluate(inputs=inputs, now=now)


def slo_state() -> List[Dict[str, Any]]:
    """Last-known local compliance rows (telemetry/scrape surface)."""
    return _engine().state()


def blocking_breaches() -> List[str]:
    """Blocking SLOs currently in breach locally (``/healthz`` consumes this)."""
    return _engine().blocking_breaches()


def reset_slo() -> None:
    """Drop windows + compliance state (``reset_engine_stats`` lockstep)."""
    if _ENGINE is not None:
        _ENGINE.reset()
