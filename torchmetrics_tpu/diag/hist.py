"""Fixed-memory log-bucketed latency/size histograms (HDR-style).

The flight recorder answers "what happened"; averages answer almost nothing
about latency — a mean dispatch time hides the p99 stall that actually gates a
pod-scale step. This module records latency *distributions* with **bounded
memory and no per-event storage**: each histogram is a fixed array of integer
bucket counts over geometrically spaced boundaries, so recording is O(log
buckets) (one ``bisect`` + one increment), a million samples cost the same
bytes as ten, and quantiles come out with a guaranteed relative error bound.

Bucket scheme (the HDR trade): boundaries grow by a constant factor
``GROWTH = 2**(1/4)`` (four sub-buckets per octave), spanning
``2**-2 .. 2**30`` — for microsecond latencies that is 0.25 µs to ~18 minutes,
for byte sizes 0.25 B to 1 GiB. The counts array is 130 fixed int slots: one
per boundary (values at or below the first bound share bucket 0 — there is no
separate underflow slot) plus one overflow slot past the top. A quantile
estimate returns the **upper bound** of the bucket holding that rank, so for
any in-range sample quantile ``q``: ``q <= estimate <= q * GROWTH`` — a
≤ 18.92% one-sided relative error, verified against exact quantiles in
``tests/test_profile.py``.

Histograms live in a process-wide registry keyed by ``(owner, kind, series)``
— e.g. ``("fused:...", "fused", "dispatch_us")`` — and are fed by the engine
hot paths only while something is observing (an active flight recorder or an
active profile scope), so the un-observed hot loop pays nothing. Series names
end in their unit (``_us``, ``_bytes``); the Prometheus exporter
(:mod:`~torchmetrics_tpu.diag.telemetry`) renders them as proper
``histogram`` families (``_bucket``/``_sum``/``_count`` with ``le`` labels)
under unit-suffixed names (``_seconds``, ``_bytes``).

``reset_histograms()`` participates in the shared
:func:`~torchmetrics_tpu.engine.stats.reset_engine_stats` lockstep so a bench
scenario can never attribute the previous scenario's tail to the fresh run.
"""

from __future__ import annotations

from bisect import bisect_left
from math import ceil
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "BOUNDS",
    "GROWTH",
    "Histogram",
    "hist_from_arrays",
    "hist_to_arrays",
    "histograms_snapshot",
    "merge_hists",
    "observe",
    "reset_histograms",
]

#: per-bucket growth factor: 4 sub-buckets per octave => <= 2**(1/4)-1 ~ 18.92%
#: one-sided relative quantile error
GROWTH = 2.0 ** 0.25

#: geometric bucket upper bounds, 2**-2 .. 2**30 in quarter-octave steps
#: (129 boundaries; +1 overflow slot). Shared by every histogram — boundaries
#: are class-level constants, per-instance memory is the counts array only.
BOUNDS: Tuple[float, ...] = tuple(2.0 ** (i / 4.0) for i in range(-8, 121))

_N = len(BOUNDS)  # counts array length is _N + 1 (last slot = overflow)


class Histogram:
    """One fixed-memory log-bucketed histogram (counts + sum + min/max)."""

    __slots__ = ("counts", "total", "sum", "min", "max")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * (_N + 1)
        self.total = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def record(self, value: float) -> None:
        """O(log buckets): one bisect + one increment. Never raises."""
        v = float(value)
        if v != v:  # NaN would silently poison sum/min/max
            return
        # bisect_left on the shared boundary tuple: first bound >= v; values
        # past the top land in the overflow slot, <= 2**-2 in bucket 0
        self.counts[bisect_left(BOUNDS, v)] += 1
        self.total += 1
        self.sum += v
        self.min = v if self.min is None or v < self.min else self.min
        self.max = v if self.max is None or v > self.max else self.max

    def quantile(self, q: float) -> Optional[float]:
        """Upper bound of the bucket holding the ``q``-quantile sample.

        Rank convention matches ``sorted(samples)[ceil(q * n) - 1]`` (the
        "higher" interpolation), so for any recorded sample the estimate is
        within ``[exact, exact * GROWTH]`` while the sample is in bucket
        range; overflow-bucket ranks return the recorded ``max`` (exact-free
        but honest — better than pretending the top boundary was the tail).
        """
        if self.total == 0:
            return None
        rank = min(self.total, max(1, ceil(q * self.total)))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return BOUNDS[i] if i < _N else self.max
        return self.max  # unreachable: cum == total >= rank

    def nonempty_buckets(self) -> List[Tuple[Optional[float], int]]:
        """Cumulative ``(upper_bound, cumulative_count)`` pairs at non-empty
        buckets; the final pair's bound is ``None`` (the +Inf bucket)."""
        out: List[Tuple[Optional[float], int]] = []
        cum = 0
        for i, c in enumerate(self.counts):
            if c:
                cum += c
                out.append((BOUNDS[i] if i < _N else None, cum))
        if not out or out[-1][0] is not None:
            out.append((None, cum))
        return out

    def as_dict(self) -> Dict[str, Any]:
        return {
            "count": self.total,
            "sum": round(self.sum, 3),
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }

    def __repr__(self) -> str:
        return f"Histogram(n={self.total}, p50={self.quantile(0.5)}, p99={self.quantile(0.99)})"


def merge_hists(a: Histogram, b: Histogram) -> Histogram:
    """Merge two histograms over the shared geometric bounds.

    Every histogram shares the class-level :data:`BOUNDS`, so the merge is an
    elementwise register (bucket-count) addition plus the scalar folds —
    commutative and associative, and exactly the histogram the union stream
    would have produced (each sample lands in the same bucket regardless of
    which pod recorded it). The quantile error bound is therefore unchanged by
    merging: a merged estimate stays within ``[exact, exact * GROWTH]`` for
    in-range samples — pinned by the property test in
    ``tests/test_federation.py``. The cross-pod composition path for the
    federated aggregation plane (``serve/federation.py``).
    """
    out = Histogram()
    out.counts = [x + y for x, y in zip(a.counts, b.counts)]
    out.total = a.total + b.total
    out.sum = a.sum + b.sum
    mins = [m for m in (a.min, b.min) if m is not None]
    maxs = [m for m in (a.max, b.max) if m is not None]
    out.min = min(mins) if mins else None
    out.max = max(maxs) if maxs else None
    return out


def hist_to_arrays(hist: Histogram) -> Tuple[List[int], List[float]]:
    """Flatten a histogram into ``(counts, [total, sum, min, max])`` lists.

    The wire form of the fleet telemetry envelope (``serve/fleet.py``): the
    counts ride as one fixed-length integer vector over the shared
    :data:`BOUNDS`, the scalar folds as a 4-float vector with NaN standing in
    for an unset min/max. Round-trips exactly through
    :func:`hist_from_arrays` — bucket geometry is a class-level constant, so
    no boundary data travels and a merged remote histogram keeps the same
    ≤ 18.92% one-sided quantile error bound as a local one.
    """
    nan = float("nan")
    meta = [
        float(hist.total),
        float(hist.sum),
        nan if hist.min is None else float(hist.min),
        nan if hist.max is None else float(hist.max),
    ]
    return list(hist.counts), meta


def hist_from_arrays(counts, meta) -> Histogram:
    """Rebuild a :class:`Histogram` from its :func:`hist_to_arrays` form."""
    counts = [int(c) for c in counts]
    if len(counts) != _N + 1:
        raise ValueError(
            f"histogram counts vector has {len(counts)} slots, expected {_N + 1}"
            " — incompatible bucket layout"
        )
    hist = Histogram()
    hist.counts = counts
    hist.total = int(meta[0])
    hist.sum = float(meta[1])
    hist.min = None if float(meta[2]) != float(meta[2]) else float(meta[2])
    hist.max = None if float(meta[3]) != float(meta[3]) else float(meta[3])
    return hist


# process-wide registry: (owner, kind, series) -> Histogram. Bounded by the
# live (owner, kind) population x ~5 series names — not by event volume.
_REGISTRY: Dict[Tuple[str, str, str], Histogram] = {}


def observe(owner: str, kind: str, series: str, value: float) -> None:
    """Record one sample into the ``(owner, kind, series)`` histogram.

    Call sites gate on "is anything observing" (active recorder or active
    profile scope) — this function itself always records.
    """
    hist = _REGISTRY.get((owner, kind, series))
    if hist is None:
        hist = _REGISTRY[(owner, kind, series)] = Histogram()
    hist.record(value)


def histograms_snapshot() -> List[Dict[str, Any]]:
    """Every live histogram as a sorted row (byte-stable JSON ordering)."""
    return [
        {"owner": owner, "kind": kind, "series": series, **hist.as_dict()}
        for (owner, kind, series), hist in sorted(_REGISTRY.items())
    ]


def histogram_items() -> List[Tuple[Tuple[str, str, str], Histogram]]:
    """Sorted live ``((owner, kind, series), Histogram)`` pairs (exporter use)."""
    return sorted(_REGISTRY.items())


def reset_histograms() -> None:
    """Drop every histogram (``reset_engine_stats`` calls this in lockstep)."""
    _REGISTRY.clear()
