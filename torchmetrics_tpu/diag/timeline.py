"""Cross-rank timeline merge and packed-sync straggler detection.

A multi-chip epoch produces one event stream per rank, each on its own host
clock. Looking at them separately hides exactly the question that matters at
pod scale: *who is late into the packed sync, and by how much?* This module
turns the per-rank streams into one picture:

- **Clock-offset estimation from the packed-sync barrier.** Each rank stamps
  two timestamps into the packed sync's existing int32 metadata gather
  (``parallel/packing.py``; zero extra collectives): its *previous* barrier
  exit (``prev_post``) and its *current* barrier arrival (``arrival``), both
  on the :func:`~torchmetrics_tpu.diag.profile.epoch_now_us` clock. All ranks
  exit a collective at approximately the same true instant, so the gathered
  ``prev_post`` stamps are simultaneous events observed on different clocks —
  their pairwise differences ARE the clock offsets (to within one collective's
  exit jitter). The entries are **layout-versioned**: a rank gathering a
  mismatched version (profiling enabled on some ranks only, or a future layout
  change) fails loud on every rank instead of mis-parsing silently.
- **Straggler attribution.** Offset-corrected arrivals put every rank's
  barrier entry on one clock: the last arrival is the straggler, and
  ``skew_us = last - first`` is how long the world waited for it. The epoch
  engine turns a skew past the configurable threshold
  (:func:`~torchmetrics_tpu.diag.profile.straggler_threshold_us`) into a
  ``sync.straggler`` flight-recorder event (rank + skew) and an
  ``EngineStats.sync_straggler_flags`` count.
- **:func:`merge_timelines`** renders N per-rank event streams as ONE
  Perfetto-loadable chrome trace: one *process* track per rank (pid = rank),
  per-owner thread tracks inside it, clock offsets applied, deterministic
  ordering — byte-identical JSON for identical inputs.

First-sync caveat: ``prev_post`` is 0 until a rank has completed one packed
sync, so the first exchange reports arrivals uncorrected (offsets all zero).
That is the honest choice — an uncalibrated skew is attributed to clock
offset, not to a phantom straggler.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from torchmetrics_tpu.diag import profile as _profile

__all__ = [
    "LAYOUT_VERSION",
    "TIMELINE_META_INTS",
    "merge_timelines",
    "resolve_arrivals",
    "stamp_arrival",
    "timeline_entries",
]

#: bump when the metadata piggyback layout changes; gathered versions must
#: agree on every rank (asymmetric profiling enablement fails loud here)
LAYOUT_VERSION = 1

#: ints appended to the packed-sync metadata per rank: [version, prev_post, arrival]
TIMELINE_META_INTS = 3

_MASK = 0x7FFFFFFF  # int32-positive µs stamps; wrap period ~35.8 minutes
_HALF = 1 << 30  # wrap-correction threshold for stamp differences


def timeline_entries() -> List[int]:
    """The int32 triple this rank stamps into the metadata gather."""
    return [
        LAYOUT_VERSION,
        _profile.last_sync_exit_us() & _MASK,
        _profile.epoch_now_us() & _MASK,
    ]


def stamp_arrival(meta_row: np.ndarray) -> np.ndarray:
    """Copy of a local metadata row with the arrival stamp refreshed to *now*.

    Test/bench helper for emulated worlds: an in-process "rank" that sleeps
    before calling this genuinely arrives late at the barrier — the planted
    straggler is a measured fact, not a forged number.
    """
    row = np.array(meta_row, dtype=np.int32, copy=True)
    row[-1] = np.int32(_profile.epoch_now_us() & _MASK)
    return row


def _wrap_diff(a: int, b: int) -> int:
    """``a - b`` on the masked µs clock, corrected for one int32 wrap."""
    d = int(a) - int(b)
    if d > _HALF:
        d -= _MASK + 1
    elif d < -_HALF:
        d += _MASK + 1
    return d


def resolve_arrivals(
    prev_post: Sequence[int], arrivals: Sequence[int], local_rank: int
) -> Dict[str, Any]:
    """Offset-correct the gathered barrier stamps and attribute the straggler.

    Returns::

        {
          "offsets_us":   per-rank clock offset vs the local clock (0s when
                          uncalibrated — some rank has no prev_post yet),
          "calibrated":   whether offsets came from a real prior barrier,
          "arrivals_us":  the raw gathered arrival stamps,
          "corrected_us": arrivals minus offsets (one clock),
          "skew_us":      last corrected arrival - first,
          "last_rank":    rank index of the last (straggling) arrival,
        }
    """
    prev = [int(x) for x in prev_post]
    arr = [int(x) for x in arrivals]
    world = len(arr)
    local_rank = int(local_rank) if 0 <= int(local_rank) < world else 0
    calibrated = all(p != 0 for p in prev)
    if calibrated:
        offsets = [_wrap_diff(p, prev[local_rank]) for p in prev]
    else:
        offsets = [0] * world
    corrected = [_wrap_diff(a, 0) - o for a, o in zip(arr, offsets)]
    last_rank = max(range(world), key=lambda r: (corrected[r], r))
    skew = max(corrected) - min(corrected)
    return {
        "offsets_us": offsets,
        "calibrated": calibrated,
        "arrivals_us": arr,
        "corrected_us": corrected,
        "skew_us": int(skew),
        "last_rank": int(last_rank),
    }


# ------------------------------------------------------------------ merge

# event kinds rendered as duration slices when they carry a measured span.
# update.scan and async.drain spans make the overlap VISIBLE: a drain slice on
# the worker's track running alongside the caller track's enqueue instants is
# the attributed overlap_us, drawn
_SPAN_KINDS = frozenset(
    {
        "update.dispatch", "fused.dispatch", "compute.dispatch",
        "collection.step", "sync.exchange", "update.scan", "async.drain",
    }
)


def _event_fields(ev: Any) -> Dict[str, Any]:
    """Normalize one event (TraceEvent or export_json-shaped dict)."""
    if isinstance(ev, dict):
        ts_us = float(ev.get("ts_us", float(ev.get("ts", 0.0)) * 1e6))
        data = {
            k: v for k, v in ev.items() if k not in ("seq", "ts", "ts_us", "kind", "owner")
        }
        return {"seq": int(ev.get("seq", 0)), "ts_us": ts_us, "kind": str(ev.get("kind", "")),
                "owner": str(ev.get("owner", "")), "data": data}
    return {"seq": ev.seq, "ts_us": ev.ts * 1e6, "kind": ev.kind, "owner": ev.owner, "data": dict(ev.data)}


def merge_timelines(
    streams: Sequence[Dict[str, Any]], path: Optional[str] = None
) -> Dict[str, Any]:
    """Merge per-rank event streams into one Perfetto-loadable chrome trace.

    Args:
        streams: one dict per rank: ``{"rank": int, "events": [...],``
            ``"clock_offset_us": float}`` — events are flight-recorder
            :class:`~torchmetrics_tpu.diag.trace.TraceEvent` objects (a
            ``recorder.snapshot()``) or ``export_json``-shaped dicts;
            ``clock_offset_us`` (default 0) is subtracted from every event
            timestamp, putting all ranks on one clock (use the packed sync's
            ``offsets_us``, or 0 for single-host emulations). A stream may
            additionally carry ``"pod": str`` (fleet streams, PR 19) — see
            below.
        path: optional file to additionally write the JSON to.

    Layout: one chrome *process* per rank (``pid = rank``, named
    ``rank <r>``), one thread track per event owner inside it (``collective``
    events get per-role tracks, same convention as ``export_chrome_trace``).
    When ANY stream carries a ``pod`` id, the whole merge switches to fleet
    layout: streams order canonically by ``(pod, rank)``, each gets its own
    process track (pids are dense indexes in that order — two pods' rank 0
    can no longer collide) named ``pod <p> · rank <r>``, so one Perfetto
    trace shows the entire fleet. Byte-stable under pod-id permutation: the
    canonical sort, not arrival order, fixes every pid.
    Events with a measured span render as complete ("X") slices ending at
    their (corrected) record timestamp. Output ordering is fully
    deterministic: identical inputs serialize byte-identically.
    """
    trace_events: List[Dict[str, Any]] = []
    flat: List[Any] = []  # (ts_us, pid, seq, tid, is_span, dur, kind, data)
    tids: Dict[Any, int] = {}

    fleet = any("pod" in s for s in streams)
    ordered = sorted(
        streams, key=lambda s: (str(s.get("pod", "")), int(s.get("rank", 0)))
    )
    for index, stream in enumerate(ordered):
        rank = int(stream.get("rank", 0))
        pod = str(stream.get("pod", ""))
        # legacy (rank-only) streams keep pid = rank; fleet streams need a
        # dense pid because rank values repeat across pods
        pid = index if fleet else rank
        name = f"pod {pod} · rank {rank}" if fleet else f"rank {rank}"
        offset = float(stream.get("clock_offset_us", 0.0))
        trace_events.append(
            {"ph": "M", "pid": pid, "name": "process_name", "args": {"name": name}}
        )
        for raw in stream.get("events", ()):
            ev = _event_fields(raw)
            if ev["kind"] == "collective":
                owner = "collective:" + str(ev["data"].get("label") or "?")
            else:
                owner = ev["owner"] or "<process>"
            tid = tids.setdefault((pid, owner), len(tids) + 1)
            ts = round(ev["ts_us"] - offset, 3)
            dur = float(ev["data"].get("dispatch_us", 0.0))
            flat.append((ts, pid, ev["seq"], tid, ev["kind"], dur, ev["data"]))

    flow_seen: Dict[Any, bool] = {}  # lineage span id -> emitted a start yet
    for ts, rank, seq, tid, kind, dur, data in sorted(flat, key=lambda x: (x[0], x[1], x[2])):
        entry: Dict[str, Any] = {
            "name": kind,
            "pid": rank,
            "tid": tid,
            "args": {k: (v if isinstance(v, (int, float, bool, str)) else str(v)) for k, v in sorted(data.items())},
        }
        if kind in _SPAN_KINDS and dur > 0.0:
            entry.update(ph="X", ts=round(ts - dur, 3), dur=round(dur, 3))
        else:
            entry.update(ph="i", ts=ts, s="t")
        trace_events.append(entry)
        span = data.get("lineage")
        if span is not None:
            # causal flow arrows (diag/lineage.py): every event stamped with
            # the same lineage span id chains enqueue → drain → join → observe
            # across thread AND process tracks — "s" opens the arrow at the
            # first occurrence in merged order, "f"/bp="e" binds each later
            # occurrence, so Perfetto draws the value's whole causal path
            flow: Dict[str, Any] = {
                "name": "lineage", "cat": "lineage", "id": int(span),
                "pid": rank, "tid": tid, "ts": entry["ts"],
            }
            if flow_seen.setdefault(span, False):
                flow.update(ph="f", bp="e")
            else:
                flow_seen[span] = True
                flow["ph"] = "s"
            trace_events.append(flow)

    for (pid, owner), tid in sorted(tids.items(), key=lambda kv: kv[1]):
        trace_events.append(
            {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name", "args": {"name": owner}}
        )

    trace = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as fh:
            json.dump(trace, fh, sort_keys=True)
    return trace
