"""Telemetry exporter — the scrapeable surface over counters, ledger, sentinels.

A production metrics stack scrapes; it does not attach a debugger. This module
renders everything the diag subsystem knows — engine counters, retrace causes,
fallback reasons, flight-recorder event counts, the cost/memory ledger, the
sentinel health states, the fixed-memory latency/size histograms
(``diag/hist.py``, exported as proper ``histogram`` families with
``_bucket``/``_sum``/``_count`` and ``le`` labels under unit-suffixed
``_seconds``/``_bytes`` names), and the profiler's probe accounting — as:

- :func:`telemetry_snapshot` — one merged, JSON-serializable dict (the
  machine-readable superset);
- :func:`export_prometheus` — Prometheus **text exposition format** (version
  0.0.4: ``# HELP``/``# TYPE`` headers, ``name{label="value"} 1.0`` samples),
  suitable for a textfile collector or a pull endpoint;
- :func:`export_jsonl` — append-one-line-per-snapshot JSON-lines, for offline
  diffing and long-running tail dashboards.

Everything is deterministically ordered (sorted metric names, sorted label
sets) so two exports of the same state are byte-identical — the counter
regression gate and the tests rely on that.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu.diag.trace import FlightRecorder, active_recorder

__all__ = [
    "UNIT_SUFFIXES",
    "UNITLESS_COUNT_FAMILIES",
    "export_jsonl",
    "export_prometheus",
    "telemetry_snapshot",
]

_PREFIX = "tm_tpu"

#: the exposition naming convention (https://prometheus.io/docs/practices/naming/):
#: a series measuring a physical quantity must spell its base unit as the name
#: suffix. This is the CANONICAL declaration — the test-suite exposition parser
#: and the static analyzer (``tools/tmlint`` rule TM403) both read it.
UNIT_SUFFIXES = ("_seconds", "_bytes", "_flops", "_ratio")

#: families whose value is a pure EVENT/OBJECT COUNT or an enum bitmask — the
#: exposition conventions require no unit suffix for those
#: (`http_requests_total` style). Any series measuring a physical quantity
#: (time, size, rate) must NOT be added here; give it a
#: `_seconds`/`_bytes`/`_flops` spelling instead. Keyed WITHOUT the `_total`
#: suffix. New counter fields must either carry a unit suffix or be
#: allowlisted here — tmlint gates the lockstep statically, the telemetry
#: round-trip test at scrape time.
UNITLESS_COUNT_FAMILIES = frozenset({
    "tm_tpu_traces", "tm_tpu_cache_hits", "tm_tpu_dispatches", "tm_tpu_metrics_updated",
    "tm_tpu_eager_fallbacks", "tm_tpu_donated_dispatches", "tm_tpu_donation_copies",
    "tm_tpu_donation_fallbacks", "tm_tpu_bucketed_steps", "tm_tpu_bucket_pad_rows",
    "tm_tpu_packed_syncs", "tm_tpu_sync_collectives", "tm_tpu_sync_metadata_gathers",
    "tm_tpu_sync_fold_traces", "tm_tpu_sync_divergence_flags", "tm_tpu_sync_straggler_flags",
    "tm_tpu_sync_retries", "tm_tpu_sync_degraded_folds",
    "tm_tpu_quarantined_batches", "tm_tpu_ladder_retries",
    # numerics layer (engine/numerics.py, PR 8): two-sum step / reanchor /
    # drift-audit event counts — pure counts, no physical unit. These four
    # existed as EngineStats fields without export rows until tmlint rule
    # TM401 flagged the drift.
    "tm_tpu_compensated_steps", "tm_tpu_reanchors", "tm_tpu_drift_probes",
    "tm_tpu_drift_flags",
    # multi-step scan dispatch (engine/scan.py, PR 10): drain/step/flush event
    # counts — pure counts, no physical unit
    "tm_tpu_scan_dispatches", "tm_tpu_scan_steps_folded", "tm_tpu_scan_pad_steps",
    "tm_tpu_scan_flushes", "tm_tpu_scan_flush_reasons",
    "tm_tpu_compute_traces", "tm_tpu_compute_dispatches", "tm_tpu_compute_cache_hits",
    "tm_tpu_profile_probes", "tm_tpu_engines", "tm_tpu_retrace_causes",
    "tm_tpu_fallback_reasons", "tm_tpu_events", "tm_tpu_events_dropped",
    "tm_tpu_ledger_executables", "tm_tpu_sentinel_flags",
    # serving layer (serve/, PR 9): scrape/snapshot event counts + live-object
    # gauges; scrape latency itself is unit-suffixed (serve_scrape_latency_seconds)
    "tm_tpu_serve_scrapes", "tm_tpu_serve_snapshots", "tm_tpu_serve_snapshot_retries",
    "tm_tpu_serve_tenants", "tm_tpu_serve_spilled_updates",
    # state-spec registry (engine/statespec.py, PR 11): deprecated-convention
    # role resolutions — a pure migration count, no physical unit
    "tm_tpu_spec_fallbacks",
    # heavy-workload kernels (image/fid.py, detection/mean_ap.py, PR 15):
    # retained host-path engagements — pure counts, no physical unit
    "tm_tpu_fid_host_eighs", "tm_tpu_map_host_evals",
    # SPMD sharded-state engine (parallel/sharding.py, PR 12): placement /
    # in-graph-sync event counts — pure counts, no physical unit
    "tm_tpu_shard_states", "tm_tpu_psum_syncs", "tm_tpu_gather_skipped",
    # 2-D data×state mesh (parallel/sharding.py + engine/epoch.py, PR 16):
    # degrade-to-replication, in-graph exchange, and no-op-plan counts
    "tm_tpu_shard_degrades", "tm_tpu_ingraph_syncs", "tm_tpu_sync_noop_plans",
    # async pipelined dispatch (engine/async_dispatch.py, PR 13): buffer /
    # drain / join / replay event counts and the in-flight-depth histogram —
    # pure counts; the time-valued async series export as *_seconds
    "tm_tpu_async_submits", "tm_tpu_async_dispatches", "tm_tpu_async_joins",
    "tm_tpu_async_backpressure_waits", "tm_tpu_async_replayed_steps",
    "tm_tpu_async_prefetches", "tm_tpu_async_queue_depth",
    # persistent executable cache (engine/persist.py, PR 17): hit / miss /
    # store / reject / replay event counts — pure counts; the time-valued
    # deserialize series exports as *_seconds, artifact sizes as *_bytes
    "tm_tpu_persist_hits", "tm_tpu_persist_misses", "tm_tpu_prewarm_replays",
    "tm_tpu_persist_stores", "tm_tpu_persist_envelope_rejects",
    "tm_tpu_persist_corrupt_skips", "tm_tpu_persist_fallbacks",
    "tm_tpu_persist_manifest_entries",
    # federated aggregation plane (serve/federation.py, PR 18): ingest / fold /
    # degraded / dedupe event counts and the live-pod gauge — pure counts
    "tm_tpu_federation_ingests", "tm_tpu_federation_folds",
    "tm_tpu_federation_degraded_folds", "tm_tpu_federation_stale_skips",
    "tm_tpu_federation_pods", "tm_tpu_federation_degraded_pods",
    # fleet observability plane (serve/fleet.py, PR 19): pull / merge /
    # exclusion event counts, membership + per-pod liveness/watermark gauges,
    # and the fleet-summed curated counter families — pure counts; the
    # time-valued per-pod gauges export as *_seconds
    "tm_tpu_fleet_pulls", "tm_tpu_fleet_merges", "tm_tpu_fleet_degraded_pulls",
    "tm_tpu_fleet_pods", "tm_tpu_fleet_degraded_pods", "tm_tpu_fleet_pod_up",
    "tm_tpu_fleet_pod_seq", "tm_tpu_fleet_pod_seq_lag",
    "tm_tpu_fleet_dispatches", "tm_tpu_fleet_eager_fallbacks",
    "tm_tpu_fleet_sync_degraded_folds", "tm_tpu_fleet_quarantined_batches",
    # declarative SLO engine (diag/slo.py, PR 19): evaluation / transition
    # event counts and the per-SLO compliance gauges — pure counts/booleans
    "tm_tpu_slo_evaluations", "tm_tpu_slo_breaches", "tm_tpu_slo_recoveries",
    "tm_tpu_slo_compliance", "tm_tpu_slo_breaching",
    # value provenance & freshness plane (diag/lineage.py, PR 20): record /
    # span / attestation event counts and the steps-behind staleness histogram
    # — pure counts; the wall-time staleness series exports as *_seconds
    "tm_tpu_lineage_records", "tm_tpu_lineage_spans",
    "tm_tpu_lineage_coverage_folds", "tm_tpu_staleness_steps",
    # build-identity info gauge: constant 1, all content in the labels
    # (the standard `*_build_info` dashboard join key)
    "tm_tpu_build_info",
})

# EngineStats fields exported as monotonic counters (everything countable);
# HELP strings double as the field glossary for scrape-side dashboards.
_COUNTER_HELP = {
    "traces": "update executables compiled",
    "cache_hits": "update steps served by a cached executable",
    "dispatches": "compiled update executions",
    "metrics_updated": "metric-updates performed via compiled steps",
    "eager_fallbacks": "steps that fell back to the eager Python path",
    "donated_dispatches": "dispatches that donated the state pytree",
    "donation_copies": "state leaves copied pre-dispatch to shield shared buffers",
    "donation_fallbacks": "dispatches that skipped donation",
    "bucketed_steps": "steps that rode a shape bucket",
    "bucket_pad_rows": "total pad rows added across bucketed steps",
    "bytes_moved": "input+state bytes entering compiled dispatches",
    "scan_dispatches": "multi-step scan drains executed (one dispatch folding many steps)",
    "scan_steps_folded": "real update steps folded across all scan drains",
    "scan_pad_steps": "masked no-op padding steps added to fill scan K-buckets",
    "scan_flushes": "scan-queue flushes (drains + discards)",
    "async_submits": "scan buffers swapped out and handed to the background drain worker",
    "async_dispatches": "background drains executed off the caller's thread",
    "async_joins": "observation joins that waited on in-flight background work",
    "async_join_wait_us": "host time observers spent waiting at async joins",
    "async_overlap_us": "drain/sync execution overlapped with caller forward progress",
    "async_backpressure_waits": "buffer submits that blocked on the bounded in-flight window",
    "async_replayed_steps": "steps replayed on the caller after a background drain failed",
    "async_prefetches": "host arrays device_put-staged at enqueue ahead of their drain",
    "quarantined_batches": "poisoned batches skipped in-graph by the quarantine transaction",
    "ladder_retries": "dispatch failures that stepped down the fallback ladder to a smaller bucket",
    "compensated_steps": "updates whose accumulate rode the in-graph two-sum",
    "reanchors": "epoch-boundary (value, residual) folds into a clean anchor",
    "drift_probes": "sampled drift-audit reads at the sanctioned boundary",
    "drift_flags": "drift probes exceeding TORCHMETRICS_TPU_DRIFT_RTOL",
    "packed_syncs": "packed epoch syncs completed",
    "sync_collectives": "buffer collectives issued across packed syncs",
    "sync_metadata_gathers": "metadata exchanges issued",
    "sync_bytes_moved": "bytes through packed-sync collectives",
    "sync_fold_traces": "fold / fused sync-compute executables compiled",
    "sync_divergence_flags": "rank-divergent rank-invariant states flagged by the audit",
    "sync_straggler_flags": "packed syncs whose arrival skew exceeded the straggler threshold",
    "sync_retries": "bounded-collective retries spent inside packed exchanges",
    "sync_degraded_folds": "packed syncs folded over a degraded (survivor) membership",
    "compute_traces": "compute executables compiled",
    "compute_dispatches": "cached compute dispatches",
    "compute_cache_hits": "compute dispatches served without a re-trace",
    "profile_probes": "warm dispatches followed by a sampled completion probe",
    "spec_fallbacks": "state roles resolved via the deprecated string-prefix/attribute conventions",
    "fid_host_eighs": "FID Frechet computes routed to the retained host-eigh fallback",
    "map_host_evals": "mAP computes evaluated by the retained host matcher",
    "shard_states": "states placed distributed via a resolved shard rule",
    "psum_syncs": "additive sharded states whose sync lowered to in-graph psum",
    "gather_skipped": "sharded states the packed host gather skipped",
    "shard_degrades": "shard-rule resolutions degraded to replication",
    "ingraph_syncs": "packed exchanges that rode the data axis in-graph",
    "sync_noop_plans": "packed syncs skipped wholesale (every state live-sharded)",
    "persist_hits": "compiles served by deserializing a persisted executable",
    "persist_misses": "compiles with no loadable persisted artifact (absent/stale/corrupt)",
    "prewarm_replays": "manifest rows replayed by prewarm before traffic landed",
    "federation_ingests": "pod snapshots accepted by the federation aggregator",
    "federation_folds": "global federation folds executed over the verified membership",
    "federation_degraded_folds": "federation folds over a degraded (pod-excluding) membership",
    "federation_stale_skips": "pod snapshots rejected by the federation watermark/staleness dedupe",
    "fleet_pulls": "pod telemetry envelopes accepted by the fleet aggregator",
    "fleet_merges": "fleet-wide telemetry merges over the fresh pod membership",
    "fleet_degraded_pulls": "pods excluded from a fleet pull/merge round (fault, stale, never pulled)",
    "slo_evaluations": "SLO evaluation passes over the registered objectives",
    "slo_breaches": "SLO compliance transitions into breach",
    "slo_recoveries": "SLO compliance transitions back to healthy",
    "lineage_records": "ValueProvenance records built at observation sites",
    "lineage_spans": "causal lineage spans opened at enqueue (one per drain generation)",
    "lineage_coverage_folds": "coverage attestations stamped at fold/merge sites",
}

# exposition-convention names for counters whose field name buries the unit:
# per https://prometheus.io/docs/practices/naming/ the base unit is the name
# SUFFIX (before _total), so `bytes_moved` exports as `moved_bytes`
_COUNTER_EXPORT_NAME = {
    "bytes_moved": "moved_bytes",
    "sync_bytes_moved": "sync_moved_bytes",
}

# µs-valued counters export in SECONDS under a unit-suffixed name (the
# exposition base-unit rule); the in-repo EngineStats fields stay integral µs
_COUNTER_EXPORT_SCALE = {
    "async_join_wait_us": ("async_join_wait_seconds", 1e-6),
    "async_overlap_us": ("async_overlap_seconds", 1e-6),
}

# histogram series (diag/hist.py, recorded in µs / bytes) -> exposition
# family name + value scale. Latencies export in SECONDS, sizes in BYTES —
# unit-suffixed per the exposition conventions (the test parser rejects
# unitless new series).
_HIST_SERIES = {
    "dispatch_us": ("dispatch_latency_seconds", 1e-6, "host wall-time of the async dispatch launch"),
    "device_us": ("device_latency_seconds", 1e-6, "sampled dispatch-to-completion latency (profiling probes)"),
    "sync_us": ("sync_latency_seconds", 1e-6, "packed-sync exchange wall-time"),
    "compute_us": ("compute_latency_seconds", 1e-6, "cached/fused compute dispatch wall-time"),
    "sync_bytes": ("sync_size_bytes", 1.0, "bytes through packed-sync collectives per exchange"),
    "scrape_us": ("serve_scrape_latency_seconds", 1e-6, "sidecar scrape handling wall-time"),
    # async dispatch (engine/async_dispatch.py): per-enqueue caller cost and
    # the in-flight buffer depth behind the background worker (a pure count —
    # allowlisted unitless, like the scan step counters)
    "enqueue_us": ("async_enqueue_latency_seconds", 1e-6, "caller-side cost of one async scan enqueue"),
    "depth": ("async_queue_depth", 1.0, "in-flight buffers pending behind the background drain worker"),
    # value provenance & freshness plane (diag/lineage.py): per-observation
    # staleness bounds. Steps-behind is a pure count (allowlisted unitless,
    # like the queue depth); the wall bound exports in seconds.
    "staleness_steps": ("staleness_steps", 1.0, "enqueued-but-unfolded steps behind at observation time"),
    "staleness_us": ("staleness_seconds", 1e-6, "wall-clock bound on observed-value age (oldest unfolded enqueue)"),
}


def _escape(value: Any) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_value(value: Any) -> str:
    """Full-precision sample rendering: ``%g`` would truncate byte/flops
    counters past 6 significant digits, silently corrupting scraped rates."""
    number = float(value)
    if number.is_integer() and abs(number) < 2**63:
        return str(int(number))
    return repr(number)


def _sample(name: str, labels: Dict[str, Any], value: Any) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {_format_value(value)}"
    return f"{name} {_format_value(value)}"


def telemetry_snapshot(recorder: Optional[FlightRecorder] = None) -> Dict[str, Any]:
    """One merged observability dict: counters + events + ledger + sentinels.

    ``recorder`` defaults to the active flight recorder (event counts are
    empty when recording is off). Purely a read — nothing is reset.
    """
    from torchmetrics_tpu.diag.costs import ledger_snapshot
    from torchmetrics_tpu.diag.hist import histograms_snapshot
    from torchmetrics_tpu.diag.lineage import lineage_snapshot
    from torchmetrics_tpu.diag.profile import profile_snapshot
    from torchmetrics_tpu.diag.sentinel import sentinel_report
    from torchmetrics_tpu.diag.slo import slo_state
    from torchmetrics_tpu.engine.persist import persist_state
    from torchmetrics_tpu.engine.stats import engine_report
    from torchmetrics_tpu.parallel.resilience import resilience_snapshot

    from torchmetrics_tpu.serve.stats import serve_state

    rec = recorder if recorder is not None else active_recorder()
    counters = engine_report()
    return {
        "counters": counters,
        "events": dict(sorted(rec.counts.items())) if rec is not None else {},
        "dropped": rec.dropped if rec is not None else 0,
        "ledger": ledger_snapshot(),
        "sentinels": sentinel_report(),
        "histograms": histograms_snapshot(),
        "profile": profile_snapshot(),
        "resilience": resilience_snapshot(),
        "serve": serve_state(),
        "persist": persist_state(),
        "slo": slo_state(),
        "provenance": lineage_snapshot(),
    }


def _build_info_labels() -> Dict[str, str]:
    """Label set for the ``tm_tpu_build_info`` gauge (value is always 1).

    The standard dashboard join key: package + jax/jaxlib versions, backend,
    device identity, and the active state-mesh shape ride as label values
    (escaped by :func:`_sample` — versions can carry ``+local`` build metadata
    and device kinds are vendor strings, so nothing here is trusted to be
    exposition-clean). Kept as its own function so tests can monkeypatch
    hostile values through the full render path.
    """
    import jax

    from torchmetrics_tpu.__about__ import __version__
    from torchmetrics_tpu.parallel.sharding import metric_mesh

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", None) or jaxlib.version.__version__
    except Exception:
        jaxlib_version = ""
    devices = jax.devices()
    mesh = metric_mesh()
    mesh_shape = ""
    if mesh is not None:
        mesh_shape = ",".join(f"{axis}={size}" for axis, size in dict(mesh.shape).items())
    return {
        "version": __version__,
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "",
        "device_count": str(len(devices)),
        "mesh": mesh_shape,
    }


def export_prometheus(path: Optional[str] = None, snapshot: Optional[Dict[str, Any]] = None) -> str:
    """Render a telemetry snapshot as Prometheus text exposition format.

    Returns the exposition text; additionally writes it to ``path`` when
    given. The output parses with any exposition-format consumer (the test
    suite round-trips it through a minimal parser).
    """
    snap = snapshot if snapshot is not None else telemetry_snapshot()
    counters = snap.get("counters", {})
    lines: List[str] = []

    def emit(name: str, mtype: str, help_text: str, samples: List[Tuple[Dict[str, Any], Any]]) -> None:
        if not samples:
            return
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            lines.append(_sample(name, labels, value))

    # build-identity join key first: constant 1, all content in the labels
    emit(f"{_PREFIX}_build_info", "gauge",
         "build/runtime identity (version, jax/jaxlib, backend, devices, mesh)",
         [(_build_info_labels(), 1)])
    for field in sorted(_COUNTER_HELP):
        if field in counters:
            scaled = _COUNTER_EXPORT_SCALE.get(field)
            if scaled is not None:
                name, scale = scaled
                emit(f"{_PREFIX}_{name}_total", "counter", _COUNTER_HELP[field],
                     [({}, counters[field] * scale)])
                continue
            name = _COUNTER_EXPORT_NAME.get(field, field)
            emit(f"{_PREFIX}_{name}_total", "counter", _COUNTER_HELP[field], [({}, counters[field])])
    emit(f"{_PREFIX}_engines", "gauge", "live engine instances", [({}, counters.get("engines", 0))])
    emit(
        f"{_PREFIX}_retrace_causes_total", "counter", "attributed causes of post-warmup compiles",
        [({"cause": c}, n) for c, n in sorted(counters.get("retrace_causes", {}).items())],
    )
    emit(
        f"{_PREFIX}_fallback_reasons_total", "counter", "eager fallbacks by reason",
        [({"reason": r}, n) for r, n in sorted(counters.get("fallback_reasons", {}).items())],
    )
    emit(
        f"{_PREFIX}_scan_flush_reasons_total", "counter", "multi-step scan-queue flushes by reason",
        [({"reason": r}, n) for r, n in sorted(counters.get("scan_flush_reasons", {}).items())],
    )
    emit(
        f"{_PREFIX}_events_total", "counter", "flight-recorder events by kind",
        [({"kind": k}, n) for k, n in sorted(snap.get("events", {}).items())],
    )
    emit(
        f"{_PREFIX}_events_dropped_total", "counter", "flight-recorder ring-buffer drops",
        [({}, snap.get("dropped", 0))],
    )

    ledger = snap.get("ledger", {})
    totals = ledger.get("totals", {})
    emit(f"{_PREFIX}_ledger_executables", "gauge", "compiled executables in the cost ledger",
         [({}, totals.get("executables", 0))])
    # unit-suffixed per the exposition conventions (seconds, not the ms the
    # in-repo ledger dicts carry — JSON exports keep their field names)
    emit(f"{_PREFIX}_ledger_compile_seconds_total", "counter", "XLA compile wall-time across executables",
         [({}, totals.get("compile_ms", 0.0) / 1e3)])
    for field, export_name, help_text in (
        ("flops", "flops", "XLA-estimated flops per execution"),
        ("bytes_accessed", "accessed_bytes", "XLA-estimated bytes accessed per execution"),
        ("peak_bytes", "peak_bytes", "peak (args+outputs+temps+code) bytes of the executable"),
        ("donation_savings_bytes", "donation_savings_bytes", "state bytes the donation avoided copying"),
    ):
        emit(
            f"{_PREFIX}_ledger_{export_name}", "gauge", help_text,
            [
                ({"owner": e["owner"], "kind": e["kind"], "signature": e["signature"]}, e[field])
                for e in ledger.get("executables", [])
                if e.get(field) is not None
            ],
        )

    emit(
        f"{_PREFIX}_sentinel_flags", "gauge", "health-sentinel bitmask per metric (0 = healthy)",
        [({"owner": s["owner"]}, s["flags"]) for s in snap.get("sentinels", [])],
    )

    # serving layer (serve/): scrape + snapshot counters and the live-object
    # gauges (tenant slots in use, sketch saturation). Scrape latency exports
    # as the serve_scrape_latency_seconds histogram family below.
    serve = snap.get("serve", {})
    emit(f"{_PREFIX}_serve_scrapes_total", "counter", "sidecar scrape requests answered",
         [({}, serve.get("scrapes", 0))])
    emit(f"{_PREFIX}_serve_scrape_seconds_total", "counter", "wall-time spent answering scrapes",
         [({}, serve.get("scrape_seconds", 0.0))])
    emit(f"{_PREFIX}_serve_snapshots_total", "counter", "pause-free state snapshots taken",
         [({}, serve.get("snapshots", 0))])
    emit(f"{_PREFIX}_serve_snapshot_retries_total", "counter",
         "snapshot attempts retried for a consistent watermark",
         [({}, serve.get("snapshot_retries", 0))])
    emit(
        f"{_PREFIX}_serve_tenants", "gauge", "live tenant slots in use per slice registry",
        [({"owner": t["owner"]}, t["tenants"]) for t in serve.get("tenancies", [])],
    )
    emit(
        f"{_PREFIX}_serve_spilled_updates_total", "counter",
        "updates spilled past tenant capacity into the heavy-hitter sketch",
        [({"owner": t["owner"]}, t["spilled"]) for t in serve.get("tenancies", [])],
    )
    emit(
        f"{_PREFIX}_serve_sketch_fill_ratio", "gauge",
        "fraction of touched sketch registers/cells (saturation)",
        [({"owner": s["owner"]}, s["fill_ratio"]) for s in serve.get("sketches", [])],
    )
    # federated aggregation plane (serve/federation.py): live/degraded pod
    # gauges per aggregator. Ingest/fold/dedupe counts ride the EngineStats
    # auto-export above (federation_ingests/folds/degraded_folds/stale_skips).
    emit(
        f"{_PREFIX}_federation_pods", "gauge",
        "pods with a verified snapshot in the federation membership",
        [({"owner": f["owner"]}, f["pods"]) for f in serve.get("federations", [])],
    )
    emit(
        f"{_PREFIX}_federation_degraded_pods", "gauge",
        "pods excluded from the last federation fold (stale/unreachable)",
        [({"owner": f["owner"]}, f["degraded_pods"]) for f in serve.get("federations", [])],
    )
    # fleet observability plane (serve/fleet.py): membership gauges per
    # aggregator. Pull/merge/exclusion counts ride the EngineStats auto-export
    # above (fleet_pulls/fleet_merges/fleet_degraded_pulls); the pod-labeled
    # per-pod series and merged tm_tpu_fleet_* families render on the fleet
    # aggregator's own exposition (FleetTelemetry.export_prometheus).
    emit(
        f"{_PREFIX}_fleet_pods", "gauge",
        "pods with fresh verified telemetry in the fleet membership",
        [({"owner": f["owner"]}, f["pods"]) for f in serve.get("fleets", [])],
    )
    emit(
        f"{_PREFIX}_fleet_degraded_pods", "gauge",
        "pods excluded from the last fleet merge (stale/unreachable)",
        [({"owner": f["owner"]}, f["degraded_pods"]) for f in serve.get("fleets", [])],
    )
    # declarative SLO engine (diag/slo.py): per-SLO compliance gauges over the
    # local evaluator's last pass. Evaluation/transition counts ride the
    # EngineStats auto-export (slo_evaluations/slo_breaches/slo_recoveries).
    emit(
        f"{_PREFIX}_slo_compliance", "gauge",
        "1 when the SLO is compliant, 0 in breach",
        [({"slo": row["id"]}, 0 if row["breaching"] else 1) for row in snap.get("slo", [])],
    )
    emit(
        f"{_PREFIX}_slo_breaching", "gauge",
        "1 when the SLO is in breach (blocking SLOs gate /healthz readiness)",
        [({"slo": row["id"]}, 1 if row["breaching"] else 0) for row in snap.get("slo", [])],
    )

    # persistent executable cache (engine/persist.py): store/reject/fallback
    # counters and the deserialize wall-time. Hit/miss/replay counts ride the
    # EngineStats auto-export above (persist_hits/persist_misses/prewarm_replays).
    persist = snap.get("persist", {})
    emit(f"{_PREFIX}_persist_stores_total", "counter",
         "executables serialized into the persistent cache",
         [({}, persist.get("stores", 0))])
    emit(f"{_PREFIX}_persist_stored_bytes_total", "counter",
         "serialized artifact bytes written to the persistent cache",
         [({}, persist.get("stored_bytes", 0))])
    emit(f"{_PREFIX}_persist_deserialize_seconds_total", "counter",
         "wall-time spent deserializing persisted executables",
         [({}, persist.get("deserialize_ms", 0.0) / 1e3)])
    emit(f"{_PREFIX}_persist_envelope_rejects_total", "counter",
         "persisted artifacts rejected for a compatibility-envelope mismatch",
         [({}, persist.get("envelope_rejects", 0))])
    emit(f"{_PREFIX}_persist_corrupt_skips_total", "counter",
         "corrupt persisted artifacts/manifest lines skipped loud",
         [({}, persist.get("corrupt_skips", 0))])
    emit(f"{_PREFIX}_persist_fallbacks_total", "counter",
         "persist-tier degradations (native-cache fallback, failed replays)",
         [({}, persist.get("fallbacks", 0))])
    emit(f"{_PREFIX}_persist_manifest_entries", "gauge",
         "prewarm-manifest rows recorded this process",
         [({}, persist.get("manifest_entries", 0))])

    # latency/size distributions as PROPER histogram exposition: cumulative
    # `_bucket` samples with `le` labels (non-empty buckets + the mandatory
    # +Inf), `_sum`, `_count`. One family per series, (owner, kind) labels.
    from torchmetrics_tpu.diag.hist import histogram_items

    by_family: Dict[str, List[Tuple[Dict[str, Any], Any]]] = {}
    for (owner, kind, series), hist in histogram_items():
        family = _HIST_SERIES.get(series)
        if family is None:
            continue
        name, scale, _ = family
        labels = {"owner": owner, "kind": kind}
        rows = by_family.setdefault(name, [])
        for bound, cum in hist.nonempty_buckets():
            le = "+Inf" if bound is None else repr(bound * scale)
            rows.append(({**labels, "le": le}, ("bucket", cum)))
        rows.append((labels, ("sum", hist.sum * scale)))
        rows.append((labels, ("count", hist.total)))
    for series, (name, _, help_text) in sorted(_HIST_SERIES.items(), key=lambda kv: kv[1][0]):
        rows = by_family.get(name)
        if not rows:
            continue
        lines.append(f"# HELP {_PREFIX}_{name} {help_text}")
        lines.append(f"# TYPE {_PREFIX}_{name} histogram")
        for labels, (suffix, value) in rows:
            lines.append(_sample(f"{_PREFIX}_{name}_{suffix}", labels, value))

    text = "\n".join(lines) + "\n" if lines else ""
    if path is not None:
        with open(path, "w") as fh:
            fh.write(text)
    return text


def export_jsonl(path: str, snapshot: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Append one snapshot as a single JSON line; returns the snapshot."""
    snap = snapshot if snapshot is not None else telemetry_snapshot()
    with open(path, "a") as fh:
        fh.write(json.dumps(snap, sort_keys=True, default=str) + "\n")
    return snap


#: minimal exposition-format sample line (used by the test-suite parser too)
SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|\d*\.\d+(?:[eE][-+]?\d+)?|Inf|NaN))$"
)
