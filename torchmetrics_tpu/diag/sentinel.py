"""In-graph health sentinels — device-side NaN/Inf/overflow detection with
zero hot-loop host transfers, plus the cross-rank divergence-audit knob.

A NaN in a metric state is invisible until ``compute()`` returns garbage —
and the classic way to look for it (``jnp.isnan(state).any()`` then a Python
``if``) is a device→host readback, exactly what the hot loop must not do.
Sentinels solve this **inside the compiled graphs**:

- every sentinel-enabled metric carries one extra int32 scalar
  (``metric._sentinel_flags``, pytree key ``__sentinel__`` inside compiled
  steps) holding a sticky bitmask;
- the engines fold :func:`update_flags` into the compiled ``update`` body
  (and :func:`value_flags` into cached/fused ``compute``), so health checking
  costs a few fused reductions per step and stays entirely on device;
- the packed sync (``parallel/packing.py``) folds the bitmask cross-rank by
  bitwise OR — per-bit max, so a flag raised on ANY rank survives the fold;
- the bitmask reaches the host only at a declared epoch-end boundary:
  :func:`read_sentinel` wraps its readback in ``transfer_allowed`` so a
  strict transfer-guarded epoch stays clean.

Bit layout (sticky — bits only ever set until :func:`reset_sentinels` or
``Metric.reset``):

======================  ====  ====================================================
``nan``                 0x01  a float state contains NaN
``pos_inf``             0x02  a float state contains +Inf (skipped for states whose
                              registered default already holds +Inf, e.g. MinMetric)
``neg_inf``             0x04  a float state contains -Inf (same default exemption)
``overflow_suspect``    0x08  an integer state's magnitude crossed half its dtype
                              range — the next epochs may wrap
``negative_count``      0x10  a sum/mean-reduced integer state went negative
                              (counts must not)
``input_poisoned``      0x20  a batch failed the quarantine admission check and
                              was skipped in-graph (``engine/txn.py``) — the
                              INPUT was poisoned but the state stayed clean, as
                              opposed to the sticky state-corruption bits above
``precision_loss``      0x40  an update's entire nonzero contribution landed
                              below the accumulator's ulp (``fl(acc + inc) ==
                              acc`` — ``engine/numerics.py``); a naive float32
                              accumulator is silently dropping increments from
                              here on (the compensated path preserves them in
                              the residual)
======================  ====  ====================================================

Enablement (first hit wins): :func:`sentinel_context` /
:func:`set_sentinel_enabled`, then the ``TORCHMETRICS_TPU_SENTINEL`` env var
(``"1"`` on, ``"0"``/unset off). Enable on EVERY rank of a world — the
sentinel scalar joins the packed sync buffers, and asymmetric enablement
would desynchronize the buffer layout.

The divergence audit (:func:`audit_context` / ``TORCHMETRICS_TPU_AUDIT``)
lives here too: it piggybacks per-state value fingerprints (crc32 of the
dtype-stable float64-cast buffer + element count) on the packed sync's int32
metadata gather and flags rank-divergent states that a metric declares
rank-invariant (``Metric._rank_invariant_states``) *before* the fold corrupts
them — see ``parallel/packing.py`` and ``docs/pages/observability.md``.
"""

from __future__ import annotations

import os
import weakref
from contextlib import contextmanager
from typing import Any, Dict, Generator, List, Optional

__all__ = [
    "SENTINEL_BITS",
    "audit_context",
    "audit_enabled",
    "ensure_flags",
    "read_sentinel",
    "reset_sentinels",
    "sentinel_context",
    "sentinel_enabled",
    "sentinel_report",
    "set_audit_enabled",
    "set_sentinel_enabled",
    "update_flags",
    "value_flags",
]

SENTINEL_ENV_VAR = "TORCHMETRICS_TPU_SENTINEL"
AUDIT_ENV_VAR = "TORCHMETRICS_TPU_AUDIT"

#: reserved pytree key for the sentinel scalar inside compiled step states —
#: aliased from the canonical declaration (engine/statespec.py RIDER_KEYS);
#: tmlint rule TM301 forbids respelling the literal outside that module
from torchmetrics_tpu.engine.statespec import SENTINEL_KEY as STATE_KEY  # noqa: E402
#: the attribute carrying the live bitmask on a metric instance
ATTR = "_sentinel_flags"

FLAG_NAN = 0x01
FLAG_POS_INF = 0x02
FLAG_NEG_INF = 0x04
FLAG_OVERFLOW = 0x08
FLAG_NEGATIVE_COUNT = 0x10
FLAG_INPUT_POISONED = 0x20
FLAG_PRECISION_LOSS = 0x40

SENTINEL_BITS = {
    "nan": FLAG_NAN,
    "pos_inf": FLAG_POS_INF,
    "neg_inf": FLAG_NEG_INF,
    "overflow_suspect": FLAG_OVERFLOW,
    "negative_count": FLAG_NEGATIVE_COUNT,
    "input_poisoned": FLAG_INPUT_POISONED,
    "precision_loss": FLAG_PRECISION_LOSS,
}

_enabled_override: Optional[bool] = None
_audit_override: Optional[bool] = None

# metrics currently carrying a sentinel scalar, for process-wide reporting.
# Keyed by id(): Metric.__hash__ covers the CURRENT state-array ids (reference
# semantics), so a hash-based WeakSet would re-insert the same metric after
# every update — an unbounded leak on the hot loop. id() is stable for the
# object's lifetime and the weak value drops the entry at collection.
_REGISTRY: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def sentinel_enabled() -> bool:
    """Whether compiled steps fold the health sentinel into their graphs."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(SENTINEL_ENV_VAR, "").strip() == "1"


def set_sentinel_enabled(value: Optional[bool]) -> None:
    """Force sentinels on/off process-wide; ``None`` restores the env/default."""
    global _enabled_override
    _enabled_override = value


@contextmanager
def sentinel_context(enabled: bool = True) -> Generator[None, None, None]:
    """Scoped sentinel enablement (tests, benches). Toggling mid-stream
    retraces the affected signatures once (``treedef-change``)."""
    global _enabled_override
    prev = _enabled_override
    _enabled_override = enabled
    try:
        yield
    finally:
        _enabled_override = prev


def audit_enabled() -> bool:
    """Whether packed-sync plans piggyback the cross-rank divergence audit."""
    if _audit_override is not None:
        return _audit_override
    return os.environ.get(AUDIT_ENV_VAR, "").strip() == "1"


def set_audit_enabled(value: Optional[bool]) -> None:
    global _audit_override
    _audit_override = value


@contextmanager
def audit_context(enabled: bool = True) -> Generator[None, None, None]:
    """Scoped divergence-audit enablement. Enable on EVERY rank — the audit
    entries extend the metadata probe, which must be layout-identical
    world-wide."""
    global _audit_override
    prev = _audit_override
    _audit_override = enabled
    try:
        yield
    finally:
        _audit_override = prev


# ------------------------------------------------------------------ flags math


def ensure_flags(metric: Any) -> Any:
    """The metric's sentinel scalar, created (and check plan cached) on first use.

    The one-time setup inspects the registered DEFAULT values to exempt
    states that legitimately hold ±Inf (MinMetric/MaxMetric-style sentinels);
    that inspection reads concrete host values, so it runs inside a
    ``transfer_allowed`` boundary — setup is once per metric, not hot-loop.
    """
    flags = getattr(metric, ATTR, None)
    if flags is None:
        import jax.numpy as jnp
        import numpy as np

        from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

        with transfer_allowed("sentinel-setup"):
            inf_ok = {}
            for name, default in metric._defaults.items():
                if isinstance(default, list):
                    inf_ok[name] = False
                    continue
                arr = np.asarray(default)
                inf_ok[name] = bool(np.isinf(arr).any()) if arr.dtype.kind == "f" else False
        metric._sentinel_inf_default = inf_ok
        flags = jnp.zeros((), jnp.int32)
        setattr(metric, ATTR, flags)
    _REGISTRY[id(metric)] = metric
    return flags


def _flag_if(cond: Any, bit: int) -> Any:
    import jax.numpy as jnp

    return jnp.where(cond, jnp.int32(bit), jnp.int32(0))


def update_flags(prev: Any, states: Dict[str, Any], metric: Any) -> Any:
    """Fold health checks over updated states into the sticky bitmask (jittable).

    Called inside the compiled update body — ``states`` are traced values, the
    checks lower into the same XLA graph as the update itself.
    """
    import jax.numpy as jnp

    from torchmetrics_tpu.utilities.data import dim_zero_mean, dim_zero_sum

    inf_exempt = getattr(metric, "_sentinel_inf_default", {})
    flags = prev
    for name, value in states.items():
        leaves = value if isinstance(value, list) else [value]
        for leaf in leaves:
            dtype = getattr(leaf, "dtype", None)
            if dtype is None:
                continue
            if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
                flags = flags | _flag_if(jnp.isnan(leaf).any(), FLAG_NAN)
                if not inf_exempt.get(name, False):
                    real = jnp.real(leaf) if jnp.issubdtype(dtype, jnp.complexfloating) else leaf
                    flags = flags | _flag_if(jnp.isposinf(real).any(), FLAG_POS_INF)
                    flags = flags | _flag_if(jnp.isneginf(real).any(), FLAG_NEG_INF)
            elif jnp.issubdtype(dtype, jnp.signedinteger):
                info = jnp.iinfo(dtype)
                half = info.max // 2
                flags = flags | _flag_if(((leaf > half) | (leaf < -half)).any(), FLAG_OVERFLOW)
                if metric._reductions.get(name) in (dim_zero_sum, dim_zero_mean):
                    flags = flags | _flag_if((leaf < 0).any(), FLAG_NEGATIVE_COUNT)
            elif jnp.issubdtype(dtype, jnp.unsignedinteger):
                info = jnp.iinfo(dtype)
                flags = flags | _flag_if((leaf > info.max // 2).any(), FLAG_OVERFLOW)
    return flags


def value_flags(prev: Any, value: Any, metric: Any = None) -> Any:
    """Fold NaN/Inf checks over a compute() result into the bitmask (jittable).

    A metric whose final value is NaN or ±Inf is unhealthy regardless of what
    its states look like (0/0 divisions surface here first). Metrics using the
    Inf-default idiom (MinMetric/MaxMetric: "no data yet" IS ±Inf) keep the
    same exemption :func:`update_flags` applies — their no-update compute
    legitimately returns the Inf default, so only NaN is checked for them.
    """
    import jax
    import jax.numpy as jnp

    check_inf = not (metric is not None and any(getattr(metric, "_sentinel_inf_default", {}).values()))
    flags = prev
    for leaf in jax.tree_util.tree_leaves(value):
        dtype = getattr(leaf, "dtype", None)
        if dtype is None or not (
            jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating)
        ):
            continue
        flags = flags | _flag_if(jnp.isnan(leaf).any(), FLAG_NAN)
        if check_inf:
            real = jnp.real(leaf) if jnp.issubdtype(dtype, jnp.complexfloating) else leaf
            flags = flags | _flag_if(jnp.isposinf(real).any(), FLAG_POS_INF)
            flags = flags | _flag_if(jnp.isneginf(real).any(), FLAG_NEG_INF)
    return flags


# ------------------------------------------------------------------ surfacing


def _bit_names(mask: int) -> List[str]:
    return [name for name, bit in SENTINEL_BITS.items() if mask & bit]


def read_sentinel(metric: Any) -> Dict[str, Any]:
    """Epoch-end host readout of a metric's sentinel — the SANCTIONED boundary.

    Returns ``{"owner", "flags", "bits"}``; ``flags == 0`` and ``bits == []``
    when the metric is healthy or carries no sentinel. The device→host read
    runs inside ``transfer_allowed`` so a strict-guarded epoch stays clean.
    """
    value = getattr(metric, ATTR, None)
    if value is None:
        return {"owner": type(metric).__name__, "flags": 0, "bits": []}
    import numpy as np

    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    with transfer_allowed("sentinel-read"):
        mask = int(np.asarray(value))
    return {"owner": type(metric).__name__, "flags": mask, "bits": _bit_names(mask)}


def sentinel_report() -> List[Dict[str, Any]]:
    """Sanctioned readout of every registered sentinel, aggregated per owner.

    Instances of the same metric class fold into ONE row (flags ORed,
    ``instances`` counted): rows are unique per ``owner`` and deterministically
    ordered — flagged owners first — regardless of registry iteration order,
    so Prometheus exports never emit duplicate label sets and repeated exports
    of the same state are byte-identical.
    """
    by_owner: Dict[str, Dict[str, Any]] = {}
    for metric in list(_REGISTRY.values()):
        row = read_sentinel(metric)
        slot = by_owner.setdefault(row["owner"], {"owner": row["owner"], "flags": 0, "instances": 0})
        slot["flags"] |= row["flags"]
        slot["instances"] += 1
    rows = [
        {"owner": o, "flags": s["flags"], "bits": _bit_names(s["flags"]), "instances": s["instances"]}
        for o, s in by_owner.items()
    ]
    rows.sort(key=lambda r: (r["flags"] == 0, r["owner"]))
    return rows


def reset_sentinels() -> None:
    """Zero every registered sentinel and clear the registry
    (``reset_engine_stats`` calls this)."""
    import jax.numpy as jnp

    for metric in list(_REGISTRY.values()):
        if getattr(metric, ATTR, None) is not None:
            setattr(metric, ATTR, jnp.zeros((), jnp.int32))
    _REGISTRY.clear()
