"""Diagnostics subsystem — flight recorder, transfer guard, telemetry,
profiling layer.

Always available, near-zero overhead when off. Eleven pieces:

- :mod:`~torchmetrics_tpu.diag.trace` — a contextvar-scoped ring-buffer flight
  recorder of structured engine events (dispatches, traces and retraces *with
  attributed cause*, packed-sync collectives with role/dtype/bytes, every
  eager fallback with its reason). Enable per scope with :func:`diag_context`
  or process-wide with ``TORCHMETRICS_TPU_TRACE=1``.
- :mod:`~torchmetrics_tpu.diag.transfer_guard` — proves the zero-host-transfer
  invariant: run the hot loop under :func:`transfer_guard` ("strict" raises on
  any device→host readback, "log" records it); sanctioned collective
  boundaries pass via :func:`transfer_allowed`.
- :mod:`~torchmetrics_tpu.diag.costs` — per-executable cost & memory ledger
  populated at compile time from XLA's own ``cost_analysis`` /
  ``memory_analysis`` (flops, bytes accessed, peak bytes, compile wall-time,
  donation savings), plus the live :func:`state_footprint` of a metric or
  collection.
- :mod:`~torchmetrics_tpu.diag.sentinel` — opt-in in-graph health sentinels:
  a per-metric int32 bitmask (NaN / ±Inf / overflow-suspect / negative-count)
  folded into the compiled update/compute graphs, ORed cross-rank by the
  packed sync, read on the host only at the sanctioned epoch-end boundary.
  Also hosts the cross-rank divergence-audit knob.
- :mod:`~torchmetrics_tpu.diag.telemetry` — the scrapeable surface:
  :func:`telemetry_snapshot` (one merged dict), :func:`export_prometheus`
  (text exposition format), :func:`export_jsonl`.
- :mod:`~torchmetrics_tpu.diag.report` — merges events with the engine
  counters into a per-metric report (:func:`diag_report`) and exports the
  stream as JSON (:func:`export_json`) or a Perfetto-loadable chrome trace
  (:func:`export_chrome_trace`).
- :mod:`~torchmetrics_tpu.diag.profile` — runtime profiling: every engine
  dispatch is annotated ``tm:<owner>:<kind>:<signature>`` for native
  XLA/Perfetto attribution, and opt-in sampled completion probes
  (:func:`profile_context` / ``TORCHMETRICS_TPU_PROFILE``) measure true
  ``device_us`` on every Nth warm dispatch without breaking the strict
  transfer guard on unsampled steps.
- :mod:`~torchmetrics_tpu.diag.hist` — fixed-memory log-bucketed latency/size
  histograms per (owner, kind): p50/p90/p99 in :func:`diag_report` /
  :func:`telemetry_snapshot`, proper ``histogram`` exposition in
  :func:`export_prometheus`.
- :mod:`~torchmetrics_tpu.diag.timeline` — cross-rank timeline merge
  (:func:`merge_timelines`: one Perfetto trace with per-rank — and, for fleet
  streams, per-pod — process tracks) and packed-sync straggler detection from
  barrier timestamps piggybacked on the metadata gather (``sync.straggler``
  events + ``EngineStats.sync_straggler_flags``).
- :mod:`~torchmetrics_tpu.diag.slo` — the declarative SLO engine:
  :data:`~torchmetrics_tpu.diag.slo.SLO_REGISTRY` objectives over existing
  histogram series / counter fields, fast+slow burn-rate windows,
  ``slo.breach``/``slo.recover`` transitions, and the blocking-SLO readiness
  input the serving sidecar's ``/healthz`` consumes.
- :mod:`~torchmetrics_tpu.diag.lineage` — the value provenance & freshness
  plane: per-owner enqueue/fold/observe watermarks, staleness bounds (steps
  AND wall-µs behind, host-side only), exclusion accounting (quarantined /
  replayed / discarded steps), causal span ids that ride the flight recorder
  into cross-rank flow arrows, and coverage stamps attesting what a degraded
  sync / federation fold / fleet merge actually includes. Every observation
  (:func:`~torchmetrics_tpu.diag.lineage.observe_metric`) yields a
  :class:`~torchmetrics_tpu.diag.lineage.ValueProvenance` record; the
  ``value-freshness`` SLO turns a stale pod into a named ``/healthz`` 503.

See ``docs/pages/observability.md`` for the event taxonomy, the retrace-cause
glossary, the ledger field glossary, the sentinel bit layout, and the
Prometheus scrape example.
"""

from torchmetrics_tpu.diag.costs import ledger_snapshot, reset_ledger, state_footprint
from torchmetrics_tpu.diag.hist import histograms_snapshot, reset_histograms
from torchmetrics_tpu.diag.lineage import (
    LINEAGE_HEADER,
    ValueProvenance,
    lineage_context,
    lineage_enabled,
    lineage_snapshot,
    observe_metric,
    provenance_of,
    reset_lineage,
    stalest_owner,
)
from torchmetrics_tpu.diag.profile import (
    profile_context,
    profile_snapshot,
    set_profile_every_n,
    set_straggler_threshold_us,
    straggler_threshold_us,
)
from torchmetrics_tpu.diag.report import diag_report, export_chrome_trace, export_json
from torchmetrics_tpu.diag.timeline import merge_timelines
from torchmetrics_tpu.diag.sentinel import (
    SENTINEL_BITS,
    audit_context,
    read_sentinel,
    reset_sentinels,
    sentinel_context,
    sentinel_report,
)
from torchmetrics_tpu.diag.slo import (
    SLO_REGISTRY,
    SLOEngine,
    SLOSpec,
    blocking_breaches,
    evaluate_slos,
    reset_slo,
    slo_context,
    slo_state,
)
from torchmetrics_tpu.diag.telemetry import export_jsonl, export_prometheus, telemetry_snapshot
from torchmetrics_tpu.diag.trace import (
    FlightRecorder,
    TraceEvent,
    active_recorder,
    attribute_retrace,
    clear_recorder,
    diag_context,
    record,
)
from torchmetrics_tpu.diag.transfer_guard import TransferGuardError, transfer_allowed, transfer_guard

__all__ = [
    "LINEAGE_HEADER",
    "SENTINEL_BITS",
    "SLO_REGISTRY",
    "FlightRecorder",
    "SLOEngine",
    "SLOSpec",
    "TraceEvent",
    "TransferGuardError",
    "ValueProvenance",
    "active_recorder",
    "attribute_retrace",
    "audit_context",
    "blocking_breaches",
    "clear_recorder",
    "diag_context",
    "diag_report",
    "evaluate_slos",
    "export_chrome_trace",
    "export_json",
    "export_jsonl",
    "export_prometheus",
    "histograms_snapshot",
    "ledger_snapshot",
    "lineage_context",
    "lineage_enabled",
    "lineage_snapshot",
    "merge_timelines",
    "observe_metric",
    "profile_context",
    "profile_snapshot",
    "provenance_of",
    "read_sentinel",
    "record",
    "reset_histograms",
    "reset_ledger",
    "reset_lineage",
    "reset_sentinels",
    "reset_slo",
    "sentinel_context",
    "sentinel_report",
    "set_profile_every_n",
    "set_straggler_threshold_us",
    "slo_context",
    "slo_state",
    "stalest_owner",
    "state_footprint",
    "straggler_threshold_us",
    "telemetry_snapshot",
    "transfer_allowed",
    "transfer_guard",
]
