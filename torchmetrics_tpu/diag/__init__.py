"""Diagnostics subsystem — engine flight recorder, transfer guard, reports.

Always available, near-zero overhead when off. Three pieces:

- :mod:`~torchmetrics_tpu.diag.trace` — a contextvar-scoped ring-buffer flight
  recorder of structured engine events (dispatches, traces and retraces *with
  attributed cause*, packed-sync collectives with role/dtype/bytes, every
  eager fallback with its reason). Enable per scope with :func:`diag_context`
  or process-wide with ``TORCHMETRICS_TPU_TRACE=1``.
- :mod:`~torchmetrics_tpu.diag.transfer_guard` — proves the zero-host-transfer
  invariant: run the hot loop under :func:`transfer_guard` ("strict" raises on
  any device→host readback, "log" records it); sanctioned collective
  boundaries pass via :func:`transfer_allowed`.
- :mod:`~torchmetrics_tpu.diag.report` — merges events with the engine
  counters into a per-metric report (:func:`diag_report`) and exports the
  stream as JSON (:func:`export_json`) or a Perfetto-loadable chrome trace
  (:func:`export_chrome_trace`).

See ``docs/pages/observability.md`` for the event taxonomy, the retrace-cause
glossary, and the Perfetto how-to.
"""

from torchmetrics_tpu.diag.report import diag_report, export_chrome_trace, export_json
from torchmetrics_tpu.diag.trace import (
    FlightRecorder,
    TraceEvent,
    active_recorder,
    attribute_retrace,
    clear_recorder,
    diag_context,
    record,
)
from torchmetrics_tpu.diag.transfer_guard import TransferGuardError, transfer_allowed, transfer_guard

__all__ = [
    "FlightRecorder",
    "TraceEvent",
    "TransferGuardError",
    "active_recorder",
    "attribute_retrace",
    "clear_recorder",
    "diag_context",
    "diag_report",
    "export_chrome_trace",
    "export_json",
    "record",
    "transfer_allowed",
    "transfer_guard",
]
