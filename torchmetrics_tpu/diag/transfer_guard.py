"""Hot-loop transfer guard — prove (or log) the zero-host-transfer invariant.

The north star demands ``update()``/``compute()`` with **zero host transfers in
the hot loop**: through a tunneled TPU every device→host readback costs ~0.6 ms
regardless of size and drops the stream into polling mode. This module makes
the invariant checkable instead of aspirational:

- :func:`transfer_guard` runs a section in ``"strict"`` mode (any observed
  device→host readback raises :class:`TransferGuardError`) or ``"log"`` mode
  (readbacks are recorded as ``transfer.host`` events in the flight recorder
  and allowed through). The bench engine/epoch scenarios and the diag tests run
  under strict mode — completing the section IS the proof of 0 host transfers.
- :func:`transfer_allowed` marks a *sanctioned* boundary inside a guarded
  section: the packed-sync collective backbone
  (:func:`~torchmetrics_tpu.parallel.packing.all_gather_backbone`) and the
  metadata exchange are the designated places where state legitimately crosses
  the host — those transfers are recorded as ``collective`` events with
  role/dtype/bytes, not flagged as violations.

Two detection layers (both installed for the guarded scope only):

1. **The native JAX guard** (``jax.transfer_guard_device_to_host``):
   authoritative on real accelerators, where any D2H copy — however reached —
   trips it. On the CPU backend it is inert: "device" buffers are host memory
   and ``np.asarray`` rides the zero-copy buffer protocol, so no transfer ever
   happens at the runtime level.
2. **A Python-level readback detector**, so the invariant is testable on the
   CPU-only CI image: scoped wrappers on ``jax.Array``'s host-materialisation
   points (the ``_value`` property behind ``float()``/``int()``/``tolist()``/
   printing, and ``__array__`` behind ``jax.device_get``) plus the
   ``numpy.asarray``/``numpy.array`` entry points (which on CPU bypass
   ``__array__`` via the buffer protocol). Coverage is the realistic readback
   surface of metric code, not every conceivable C-level escape hatch — on
   accelerators layer 1 closes the gap.

The hooks are installed on entry and fully removed on exit (refcounted for
nesting), so un-guarded code pays nothing. Guarded sections are expected to be
single-threaded (bench scenarios, tests); the mode itself is contextvar-scoped.
"""

from __future__ import annotations

from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Generator

from torchmetrics_tpu.diag import trace

__all__ = [
    "TRANSFER_LABELS",
    "TRANSFER_LABEL_PREFIXES",
    "TransferGuardError",
    "native_reentry",
    "transfer_allowed",
    "transfer_guard",
]

_MODES = ("strict", "log")

#: The registry of SANCTIONED host-transfer boundary labels. Every
#: ``transfer_allowed("<label>")`` call site in the package — and every
#: ``# tmlint: boundary(<label>)`` function annotation asserting "this helper
#: only runs inside that boundary" — must name a label declared here; the
#: static analyzer (``tools/tmlint`` rule TM103) rejects unregistered labels,
#: so a new host-readback boundary is a REVIEWED, named decision, not a
#: drive-by ``transfer_allowed()``. The label glossary lives in
#: ``docs/pages/static-analysis.md``.
TRANSFER_LABELS = frozenset({
    # packed-sync backbone (parallel/packing.py, engine/epoch.py)
    "sync-metadata",   # the one metadata gather covering every dynamic state
    "sync-audit",      # divergence-audit fingerprint reads on the metadata path
    "sync-fault",      # classified-fault payload inspection (parallel/resilience.py)
    # engine evidence boundaries (engine/, diag/)
    "profile-probe",   # sampled block_until_ready completion probes (PR 5)
    "drift-probe",     # sampled compensated-drift audit reads (PR 8)
    "quarantine-check",  # =error admission precheck before any mutation (PR 7)
    "quarantine-read",   # sanctioned epoch-end quarantine-counter flush (PR 7)
    "sentinel-setup",  # one-time Inf-default detection at sentinel install (PR 4)
    "sentinel-read",   # sanctioned sentinel bitmask read (PR 4)
    "group-discovery",  # one-time compute-group value comparison (collections.py)
    # checkpoint/restore boundaries (parallel/elastic.py)
    "snapshot-save",   # state materialization into an atomic .npz shard
    "snapshot-load",   # shard payload reads on the restore/reshard path
    # fault injection (parallel/faults.py) — corrupts an already-gathered row
    "fault-inject",
    # serving boundaries (serve/)
    "serve-setup",     # one-time np capture of nested-metric defaults (PR 9)
    "serve-scrape",    # scrape-path host reads with the snapshot retry protocol
    "federation-ingest",  # pod-snapshot envelope (de)serialization at the aggregation tier
    # heavy-workload retained host paths (PR 15) — counted fallbacks, declared
    "fid-host-eigh",   # FID Fréchet on host LAPACK (TORCHMETRICS_TPU_FID_HOST_EIGH)
    "fid-sample-guard",  # FID's epoch-boundary <2-sample check (two scalar reads)
    "map-host-matcher",  # mAP list/RLE host evaluator's one batched epoch-end fetch
})

#: label PREFIXES sanctioned with a dynamic suffix: the collective backbone
#: labels every buffer exchange ``collective:<role>:<dtype>`` at runtime
TRANSFER_LABEL_PREFIXES = ("collective:",)


class TransferGuardError(RuntimeError):
    """A device→host readback happened inside a strict transfer-guard scope."""


_MODE_VAR: "ContextVar[str]" = ContextVar("tm_tpu_transfer_guard_mode", default="off")
_ALLOW_VAR: "ContextVar[int]" = ContextVar("tm_tpu_transfer_allow_depth", default=0)

# hook refcount + saved originals (module-level: installation is process-global,
# activation is contextvar-scoped)
_install_depth = 0
_saved: dict = {}


def _observe(op: str) -> None:
    """Handle one observed readback under the active mode."""
    mode = _MODE_VAR.get()
    if mode == "off" or _ALLOW_VAR.get() > 0:
        return
    if mode == "log":
        trace.record("transfer.host", "", op=op)
        return
    trace.record("transfer.blocked", "", op=op)
    raise TransferGuardError(
        f"device->host readback via {op!r} inside a strict transfer-guard scope."
        " The metric hot loop must not fetch device values; move the readback"
        " to the epoch boundary, or wrap a sanctioned collective/export point"
        " in torchmetrics_tpu.diag.transfer_allowed()."
    )


def _install_hooks() -> None:
    """Wrap the host-readback entry points (refcounted; idempotent)."""
    global _install_depth
    _install_depth += 1
    if _install_depth > 1:
        return
    import numpy as np

    import jax._src.array as _jarray

    impl = _jarray.ArrayImpl
    orig_value = impl.__dict__["_value"]
    orig_array = impl.__dict__["__array__"]
    orig_asarray = np.asarray
    orig_nparray = np.array
    _saved.update(
        {"_value": orig_value, "__array__": orig_array, "asarray": orig_asarray, "array": orig_nparray}
    )

    def guarded_value(self):  # noqa: ANN001 — property fget
        _observe("jax.Array._value")
        return orig_value.fget(self)

    def guarded_dunder_array(self, *args: Any, **kwargs: Any):
        _observe("jax.Array.__array__")
        return orig_array(self, *args, **kwargs)

    # signature-transparent wrappers: numpy's first parameters are positional
    # in practice but legally keyword (`np.asarray(a=x)`, `np.array(object=x)`),
    # and third-party code must keep working unchanged inside a guarded scope
    def guarded_asarray(*args: Any, **kwargs: Any):
        a = args[0] if args else kwargs.get("a")
        if isinstance(a, impl):
            _observe("np.asarray")
        return orig_asarray(*args, **kwargs)

    def guarded_nparray(*args: Any, **kwargs: Any):
        a = args[0] if args else kwargs.get("object")
        if isinstance(a, impl):
            _observe("np.array")
        return orig_nparray(*args, **kwargs)

    impl._value = property(guarded_value)
    impl.__array__ = guarded_dunder_array
    np.asarray = guarded_asarray
    np.array = guarded_nparray


def _uninstall_hooks() -> None:
    global _install_depth
    _install_depth -= 1
    if _install_depth > 0:
        return
    import numpy as np

    import jax._src.array as _jarray

    impl = _jarray.ArrayImpl
    impl._value = _saved["_value"]
    impl.__array__ = _saved["__array__"]
    np.asarray = _saved["asarray"]
    np.array = _saved["array"]
    _saved.clear()


@contextmanager
def transfer_guard(mode: str = "strict") -> Generator[None, None, None]:
    """Run a section with device→host readbacks disallowed (or logged).

    Args:
        mode: ``"strict"`` — any readback raises :class:`TransferGuardError`
            (and is recorded as a ``transfer.blocked`` event);
            ``"log"`` — readbacks are recorded as ``transfer.host`` events and
            allowed through.

    The native JAX device-to-host guard engages alongside the Python detector:
    on real accelerators it catches transfer paths no Python hook can see.
    """
    if mode not in _MODES:
        raise ValueError(f"transfer_guard mode must be one of {_MODES}, got {mode!r}")
    import jax

    _install_hooks()
    token = _MODE_VAR.set(mode)
    try:
        with jax.transfer_guard_device_to_host("disallow" if mode == "strict" else "log"):
            yield
    finally:
        _MODE_VAR.reset(token)
        _uninstall_hooks()


@contextmanager
def native_reentry() -> Generator[None, None, None]:
    """Re-arm the native JAX D2H guard from the propagated contextvar mode.

    The Python-level detector rides contextvars and crosses threads via
    ``contextvars.copy_context`` (the async drain worker runs work items in
    the submitting scope's context), but the native jax guard is
    THREAD-local — a background drain must re-enter it explicitly or a
    guarded section's proof would not cover the worker on real accelerators.
    No-op when no guard scope is active.
    """
    mode = _MODE_VAR.get()
    if mode == "off":
        yield
        return
    import jax

    with jax.transfer_guard_device_to_host("disallow" if mode == "strict" else "log"):
        yield


@contextmanager
def transfer_allowed(label: str = "") -> Generator[None, None, None]:
    """Sanction a host-transfer boundary inside a guarded section.

    Used by the packed-sync backbone around its collectives and the metadata
    exchange — the declared places where state must cross the host. Transfers
    inside this scope pass both detection layers without raising or logging a
    violation (they are separately recorded as ``collective`` events).
    """
    depth_token = _ALLOW_VAR.set(_ALLOW_VAR.get() + 1)
    try:
        if _MODE_VAR.get() == "off":
            yield
        else:
            import jax

            with jax.transfer_guard_device_to_host("allow"):
                yield
    finally:
        _ALLOW_VAR.reset(depth_token)
