"""Runtime profiling layer — device-time attribution and sampled completion probes.

Every ``dispatch_us`` the flight recorder measured before this PR was host
wall-time around an **asynchronous** dispatch: it tells you what the launch
cost, not where device time went. This module closes that gap three ways
without breaking the zero-host-transfer invariant on unsampled steps:

1. **Attribution scopes.** The engines wrap every compiled dispatch in a
   ``jax.profiler.TraceAnnotation`` named ``tm:<owner>:<kind>:<signature>``
   (and trace their update/compute bodies under ``jax.named_scope``), so a
   native XLA/Perfetto profile (``jax.profiler.trace``) attributes device
   slices to the metric that owns them — no torchmetrics-side timing needed.
2. **Sampled completion probes.** With profiling active, every Nth *warm*
   dispatch is followed by a ``jax.block_until_ready`` at a
   ``transfer_allowed``-sanctioned boundary: the measured wait is the true
   completion latency (``device_us``) alongside the launch cost
   (``dispatch_us``). Unsampled steps are untouched — the strict transfer
   guard holds exactly as before, and the probe overhead is analytically
   bounded by ``per-probe wait x 1/every_n`` (gated < 2% in CI).
3. **The cross-rank clock.** :func:`epoch_now_us` is the per-process
   microsecond clock the packed-sync timeline piggyback
   (:mod:`~torchmetrics_tpu.diag.timeline`, ``parallel/packing.py``) stamps
   into the int32 metadata gather; :func:`note_sync_exit` marks the
   barrier-exit instant that anchors cross-rank clock-offset estimation.

Enablement (first hit wins): an active :func:`profile_context` scope, a
:func:`set_profile_every_n` override, then the ``TORCHMETRICS_TPU_PROFILE``
env var — ``"1"`` enables sampling at the default rate (every
``DEFAULT_EVERY_N`` warm dispatches), an integer > 1 sets ``every_n``,
``"0"``/unset disables. Like the sentinel and audit knobs, profiling extends
the packed-sync metadata layout: **enable it on every rank or none** (the
layout version stamped into the gather fails loud on mismatch).

The straggler threshold (``sync.straggler`` events +
``EngineStats.sync_straggler_flags`` when a rank's corrected barrier arrival
trails the world by more than the threshold) lives here too:
``TORCHMETRICS_TPU_STRAGGLER_US`` / :func:`set_straggler_threshold_us`,
default 1000 µs.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from contextvars import ContextVar
from time import perf_counter
from typing import Any, Dict, Generator, Optional, Tuple

__all__ = [
    "DEFAULT_EVERY_N",
    "PROFILE_ENV_VAR",
    "STRAGGLER_ENV_VAR",
    "active_profile",
    "epoch_now_us",
    "note_probe",
    "note_sync_exit",
    "probe_due",
    "profile_context",
    "profile_snapshot",
    "reset_profile",
    "set_profile_every_n",
    "set_straggler_threshold_us",
    "straggler_threshold_us",
    "timeline_enabled",
]

#: env knob: "1" = sample every DEFAULT_EVERY_N warm dispatches, int > 1 =
#: every_n, "0"/unset = off
PROFILE_ENV_VAR = "TORCHMETRICS_TPU_PROFILE"
DEFAULT_EVERY_N = 16

#: env knob: arrival-skew threshold (µs) past which a packed sync records a
#: ``sync.straggler`` event and bumps ``EngineStats.sync_straggler_flags``
STRAGGLER_ENV_VAR = "TORCHMETRICS_TPU_STRAGGLER_US"
DEFAULT_STRAGGLER_US = 1000.0

_PROFILE_VAR: "ContextVar[Optional[int]]" = ContextVar("tm_tpu_profile_every_n", default=None)
_every_n_override: Optional[int] = None
_straggler_override: Optional[float] = None

# (env_value, parsed) cache — a steady env var costs one read + compare per call
_env_state: Tuple[str, Optional[int]] = ("", None)

# probe accounting: (owner, kind) -> counts. Bounded by the live engine
# population; cleared by reset_profile() in the reset_engine_stats lockstep.
_dispatch_counts: Dict[Tuple[str, str], int] = {}
_probe_counts: Dict[Tuple[str, str], int] = {}
_probe_wait_us: Dict[Tuple[str, str], float] = {}

# the per-process microsecond clock timeline timestamps ride (int32-safe via
# masking in timeline.py); one epoch per process keeps every stamp comparable
_T0 = perf_counter()

# barrier-exit anchor: local timestamp at the end of the previous packed-sync
# exchange. All ranks exit a collective at (approximately) the same true
# instant, so gathering each rank's *previous* exit stamp next sync estimates
# per-rank clock offsets without any extra collective.
_last_sync_exit_us = 0


def _parse_env(raw: str) -> Optional[int]:
    if not raw or raw == "0":
        return None
    try:
        n = int(raw)
    except ValueError:
        return DEFAULT_EVERY_N
    return n if n > 1 else DEFAULT_EVERY_N


def active_profile() -> Optional[int]:
    """The active sampling rate (``every_n``), or None when profiling is off."""
    scoped = _PROFILE_VAR.get()
    if scoped is not None:
        return scoped
    if _every_n_override is not None:
        return _every_n_override
    global _env_state
    raw = os.environ.get(PROFILE_ENV_VAR, "").strip()
    if raw != _env_state[0]:
        _env_state = (raw, _parse_env(raw))
    return _env_state[1]


def set_profile_every_n(every_n: Optional[int]) -> None:
    """Force the sampling rate process-wide; ``None`` restores env/default."""
    global _every_n_override
    if every_n is not None and (not isinstance(every_n, int) or every_n < 1):
        raise ValueError(f"every_n must be a positive int or None, got {every_n!r}")
    _every_n_override = every_n


@contextmanager
def profile_context(every_n: int = DEFAULT_EVERY_N) -> Generator[None, None, None]:
    """Scoped profiling: sample every ``every_n``-th warm dispatch.

    Enable on EVERY rank of a multi-process world (the packed-sync timeline
    entries extend the metadata layout; the stamped layout version fails loud
    on asymmetric enablement). ``every_n=1`` probes every warm dispatch —
    useful in tests, ruinous on a real async pipeline.
    """
    if not isinstance(every_n, int) or every_n < 1:
        raise ValueError(f"every_n must be a positive int, got {every_n!r}")
    token = _PROFILE_VAR.set(every_n)
    try:
        yield
    finally:
        _PROFILE_VAR.reset(token)


def timeline_enabled() -> bool:
    """Whether packed syncs stamp cross-rank timeline entries (= profiling on)."""
    return active_profile() is not None


# ------------------------------------------------------------------ probes


def probe_due(owner: str, kind: str) -> bool:
    """Count one warm dispatch for ``(owner, kind)``; True on every Nth.

    Callers invoke this only when profiling is active and the dispatch is
    warm (cache-hit) — cold dispatches fold compile time into their latency
    and would poison the device-time distribution.
    """
    every_n = active_profile()
    if every_n is None:
        return False
    key = (owner, kind)
    n = _dispatch_counts.get(key, 0) + 1
    _dispatch_counts[key] = n
    return n % every_n == 0


def note_probe(owner: str, kind: str, wait_us: float) -> None:
    """Account one completed probe and its blocking wait."""
    key = (owner, kind)
    _probe_counts[key] = _probe_counts.get(key, 0) + 1
    _probe_wait_us[key] = _probe_wait_us.get(key, 0.0) + float(wait_us)


def profile_snapshot() -> Dict[str, Any]:
    """Probe accounting (deterministically sorted; byte-stable JSON)."""
    per_site = {
        f"{owner}:{kind}": {
            "warm_dispatches": _dispatch_counts.get((owner, kind), 0),
            "probes": _probe_counts.get((owner, kind), 0),
            "wait_us": round(_probe_wait_us.get((owner, kind), 0.0), 3),
        }
        for owner, kind in sorted(set(_dispatch_counts) | set(_probe_counts))
    }
    return {
        "active": active_profile() is not None,
        "every_n": active_profile(),
        "probes": sum(_probe_counts.values()),
        "probe_wait_us": round(sum(_probe_wait_us.values()), 3),
        "per_site": per_site,
    }


def reset_profile() -> None:
    """Zero the probe accounting (``reset_engine_stats`` lockstep); the
    enablement knobs are configuration, not measurement, and survive."""
    _dispatch_counts.clear()
    _probe_counts.clear()
    _probe_wait_us.clear()


# ------------------------------------------------------------------ clock


def epoch_now_us() -> int:
    """Microseconds since this process's profile epoch (monotonic clock)."""
    return int((perf_counter() - _T0) * 1e6)


def note_sync_exit() -> None:
    """Mark 'now' as the barrier-exit instant of the just-finished packed sync."""
    global _last_sync_exit_us
    _last_sync_exit_us = epoch_now_us()


def last_sync_exit_us() -> int:
    """The previous packed sync's barrier-exit stamp (0 = no sync yet)."""
    return _last_sync_exit_us


# ------------------------------------------------------------------ straggler


def straggler_threshold_us() -> float:
    """Arrival-skew threshold (µs) for flagging a packed-sync straggler."""
    if _straggler_override is not None:
        return _straggler_override
    raw = os.environ.get(STRAGGLER_ENV_VAR, "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_STRAGGLER_US


def set_straggler_threshold_us(value: Optional[float]) -> None:
    """Override the straggler threshold; ``None`` restores env/default."""
    global _straggler_override
    _straggler_override = None if value is None else float(value)
