"""Flight recorder — a contextvar-scoped ring buffer of structured engine events.

The engines (``engine/compiled.py``, ``engine/fusion.py``, ``engine/epoch.py``)
and the packed-sync plan (``parallel/packing.py``) emit structured events at
every decision point of the hot path: compiled dispatches, traces and
*retraces with an attributed cause*, packed-sync exchanges, individual
collectives with role/dtype/bytes, every eager fallback with its reason, and
host transfers observed by :mod:`torchmetrics_tpu.diag.transfer_guard`. The
recorder turns "why did this step retrace?" and "did anything read back to the
host?" from guesswork into recorded facts.

Design constraints (this module is on the per-step hot path):

- **Near-zero overhead when off.** :func:`record` costs one ``ContextVar.get``
  plus one dict lookup when no recorder is active (~0.2 µs); engine call sites
  that emit several events per step fetch :func:`active_recorder` once and
  skip event construction entirely when it returns ``None``.
- **Bounded memory.** Events land in a ``deque(maxlen=capacity)`` ring buffer;
  the oldest events are dropped (counted in ``dropped``) while per-kind counts
  stay exact regardless of drops.
- **Import-light.** No ``jax`` / ``numpy`` at module level — the recorder is
  importable from anywhere in the package without ordering hazards.

Enablement (first hit wins):

1. an active :func:`diag_context` scope (tests, benches, notebooks);
2. the ``TORCHMETRICS_TPU_TRACE`` env var — ``"1"`` enables a process-global
   recorder with the default capacity, an integer > 1 sets the capacity,
   ``"0"``/unset disables.

Event taxonomy (the ``kind`` field; full glossary in
``docs/pages/observability.md``):

=====================  ========================================================
``update.trace``       first compile of an update signature (``cause="initial"``)
``update.retrace``     a later compile — ``cause`` attributes it (see below)
``update.dispatch``    one compiled update execution (``dispatch_us``, donation info)
``update.probe``       a sampled completion probe (``device_us`` — true latency)
``update.eager``       an update that ran the eager Python body (``dispatch_us``)
``fused.trace/retrace/dispatch/probe``  the collection-fused analogues
``fused.exclude``      a member excluded from the fused executable (``reason``)
``sync.exchange``      one packed sync exchange (world, buffers, metadata)
``collective``         one backbone collective (``label`` = role:dtype, bytes)
``sync.fold_trace/fold_retrace``  fold executable compiles (``cause``)
``sync.eager``         a sync that fell back to the per-tensor eager path
``sync.audit``         a divergence-audit finding (``attr``, ``flag``)
``sync.straggler``     a packed sync whose corrected arrival skew crossed the
                       threshold (``rank`` = the straggler, ``skew_us``)
``compute.trace/retrace``  compute executable compiles (``cause``)
``compute.dispatch``   one cached/fused compute execution (``dispatch_us``)
``compute.probe``      a sampled compute completion probe (``device_us``)
``collection.step``    one MetricCollection update step (``dispatch_us``, ``owners``, ``fused``)
``async.enqueue``      one scan buffer handed to the background drain worker
                       (``steps``, ``depth`` = in-flight buffers behind it)
``async.drain``        one background drain executed off the caller's thread
                       (``dispatch_us``, ``overlap_us`` = the slice during
                       which no caller was blocked on it)
``async.join``         an observation that waited on in-flight background
                       work (``wait_us``, ``steps`` settled)
``async.sync.overlap`` a packed epoch sync whose completion window overlapped
                       the next epoch's enqueues (``overlap_us``)
``fallback``           every eager fallback, with its reason string
``transfer.host``      a device→host readback observed in ``log`` guard mode
``transfer.blocked``   a readback the ``strict`` guard refused
=====================  ========================================================

Timing fields: ``dispatch_us`` is HOST wall-time around an **asynchronous**
dispatch — the launch cost, not device time. True completion latency is
``device_us``, measured
only on sampled probe events (:mod:`torchmetrics_tpu.diag.profile`).

Retrace causes (:func:`attribute_retrace`): ``bucket-miss``, ``dtype-change``,
``treedef-change``, ``shape-change``, ``plan-change``, ``device-change`` —
attributed by diffing the new signature fingerprint against the nearest
previously-compiled one.
"""

from __future__ import annotations

import os
from collections import Counter, deque
from contextlib import contextmanager
from contextvars import ContextVar
from time import perf_counter
from typing import Any, Dict, Generator, List, NamedTuple, Optional, Sequence

__all__ = [
    "EVENT_KINDS",
    "FlightRecorder",
    "TraceEvent",
    "active_recorder",
    "attribute_retrace",
    "clear_recorder",
    "diag_context",
    "record",
]

DEFAULT_CAPACITY = 2048

#: The closed event taxonomy — every ``kind`` any call site may record. This
#: is the single declaration the static analyzer (``tools/tmlint`` rule TM501)
#: checks every ``record(...)`` literal against, and every member must be
#: documented in ``docs/pages/observability.md`` (TM503). Adding an event kind
#: means adding it HERE and to the docs table in the same change — an
#: undeclared kind fails CI from the source text, before any run records it.
EVENT_KINDS = frozenset({
    # compiled update engine (engine/compiled.py)
    "update.trace", "update.retrace", "update.dispatch", "update.probe", "update.eager",
    "update.quarantine", "update.ladder",
    # multi-step scan dispatch (engine/scan.py)
    "update.scan", "update.scan.trace", "update.scan.retrace", "update.scan.probe",
    "scan.flush",
    # async pipelined dispatch (engine/scan.py + engine/async_dispatch.py)
    "async.enqueue", "async.drain", "async.join", "async.sync.overlap",
    # collection fusion (engine/fusion.py, collections.py)
    "fused.trace", "fused.retrace", "fused.dispatch", "fused.probe", "fused.exclude",
    "collection.step",
    # epoch engine / packed sync (engine/epoch.py, parallel/packing.py)
    "sync.exchange", "sync.fold_trace", "sync.fold_retrace", "sync.eager",
    "sync.audit", "sync.straggler", "sync.retry", "sync.fault", "sync.degraded",
    "sync.shard_skip", "sync.ingraph", "sync.noop", "collective",
    # cached compute (engine/epoch.py)
    "compute.trace", "compute.retrace", "compute.dispatch", "compute.probe",
    # numerics layer (engine/numerics.py)
    "numerics.drift", "numerics.reanchor",
    # elastic checkpoints (parallel/elastic.py)
    "snapshot.save", "snapshot.restore", "snapshot.fallback", "snapshot.flush",
    "snapshot.preempt", "snapshot.restore_latest",
    # SPMD sharded-state engine (parallel/sharding.py)
    "shard.place", "shard.fallback", "shard.reshard", "multihost.init",
    # state-spec registry (engine/statespec.py)
    "spec.fallback",
    # heavy-workload kernels (image/fid.py, detection/mean_ap.py): a retained
    # host path engaged — the knob-selected FID host eigh or the host matcher
    "heavy.fallback",
    # serving layer (serve/)
    "serve.scrape", "serve.scrape.async", "serve.scrape.error", "serve.sidecar.start",
    "serve.snapshot", "serve.snapshot.read",
    # federated multi-pod aggregation plane (serve/federation.py)
    "federation.ingest", "federation.fold", "federation.degraded",
    "federation.stale", "federation.rejoin",
    # fleet observability plane (serve/fleet.py): cross-pod telemetry federation
    "fleet.pull", "fleet.merge", "fleet.degraded", "fleet.stale",
    # declarative SLO engine (diag/slo.py): breach/recover transitions
    "slo.breach", "slo.recover",
    # engine-wide fallbacks + transfer guard (engine/stats.py, diag/transfer_guard.py)
    "fallback", "transfer.host", "transfer.blocked",
    # persistent executable cache + prewarm (engine/persist.py)
    "persist.save", "persist.load", "persist.fallback", "persist.prewarm", "persist.manifest",
    # value provenance & freshness plane (diag/lineage.py)
    "lineage.observe", "lineage.coverage",
})

#: env knob: "1" = on (default capacity), int > 1 = capacity, "0"/unset = off
TRACE_ENV_VAR = "TORCHMETRICS_TPU_TRACE"


class TraceEvent(NamedTuple):
    """One recorded event. ``ts`` is seconds since the recorder's epoch."""

    seq: int
    ts: float
    kind: str
    owner: str
    data: Dict[str, Any]


class FlightRecorder:
    """Bounded ring buffer of :class:`TraceEvent` with exact per-kind counts."""

    __slots__ = ("capacity", "events", "counts", "dropped", "t0", "_seq")

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = int(capacity)
        self.events: "deque[TraceEvent]" = deque(maxlen=self.capacity)
        self.counts: Counter = Counter()
        self.dropped = 0
        self.t0 = perf_counter()
        self._seq = 0

    def record(self, kind: str, owner: str = "", **data: Any) -> None:
        """Append one event; O(1), never raises for capacity reasons."""
        if len(self.events) == self.capacity:
            self.dropped += 1
        self._seq += 1
        self.counts[kind] += 1
        self.events.append(TraceEvent(self._seq, perf_counter() - self.t0, kind, owner, data))

    def snapshot(self) -> List[TraceEvent]:
        """Stable copy of the buffered events (oldest first)."""
        return list(self.events)

    def clear(self) -> None:
        self.events.clear()
        self.counts.clear()
        self.dropped = 0
        self._seq = 0
        self.t0 = perf_counter()

    def count(self, *kinds: str) -> int:
        """Total recorded events of the given kinds (drop-proof)."""
        return sum(self.counts.get(k, 0) for k in kinds)

    def __len__(self) -> int:
        return len(self.events)

    def __repr__(self) -> str:
        return f"FlightRecorder(events={len(self.events)}, kinds={dict(self.counts)}, dropped={self.dropped})"


_RECORDER_VAR: "ContextVar[Optional[FlightRecorder]]" = ContextVar("tm_tpu_diag_recorder", default=None)

# process-global recorder backing TORCHMETRICS_TPU_TRACE; (env_value, recorder)
# cached so a steady env var costs one os.environ read + string compare per call
_env_state: tuple = ("", None)


def _env_recorder() -> Optional[FlightRecorder]:
    global _env_state
    raw = os.environ.get(TRACE_ENV_VAR, "").strip()
    if raw == _env_state[0]:
        return _env_state[1]
    rec: Optional[FlightRecorder] = None
    if raw and raw != "0":
        try:
            cap = int(raw)
        except ValueError:
            cap = DEFAULT_CAPACITY
        rec = FlightRecorder(cap if cap > 1 else DEFAULT_CAPACITY)
    _env_state = (raw, rec)
    return rec


def active_recorder() -> Optional[FlightRecorder]:
    """The recorder events go to right now, or None when recording is off."""
    rec = _RECORDER_VAR.get()
    if rec is not None:
        return rec
    return _env_recorder()


def record(kind: str, owner: str = "", **data: Any) -> None:
    """Record one event if recording is active; near-free otherwise."""
    rec = active_recorder()
    if rec is not None:
        rec.record(kind, owner, **data)


def clear_recorder() -> None:
    """Clear the active recorder's ring buffer (no-op when recording is off)."""
    rec = active_recorder()
    if rec is not None:
        rec.clear()


@contextmanager
def diag_context(
    capacity: int = DEFAULT_CAPACITY, recorder: Optional[FlightRecorder] = None
) -> Generator[FlightRecorder, None, None]:
    """Scoped recording: installs (and yields) a flight recorder.

    Nested scopes stack — events go to the innermost recorder only, and the
    outer scope resumes on exit. Pass an existing ``recorder`` to accumulate
    several scopes into one buffer.
    """
    rec = recorder if recorder is not None else FlightRecorder(capacity)
    token = _RECORDER_VAR.set(rec)
    try:
        yield rec
    finally:
        _RECORDER_VAR.reset(token)


# ------------------------------------------------------------------ retrace cause

# field -> cause, in attribution priority order: a structural (treedef) change
# outranks a dtype change outranks a bucket miss outranks a plain shape change —
# e.g. the x64 warmup promotes state dtypes AND (bucketed) shapes; the dtype is
# the actionable cause.
_CAUSE_BY_FIELD = (
    ("treedef", "treedef-change"),
    ("dtype", "dtype-change"),
    ("bucket", "bucket-miss"),
    ("shape", "shape-change"),
    ("plan", "plan-change"),
    ("device", "device-change"),
)


def attribute_retrace(new: Dict[str, Any], previous: Sequence[Dict[str, Any]]) -> str:
    """Attribute a re-compile by diffing ``new`` against prior fingerprints.

    ``new``/``previous`` are signature *fingerprints*: small dicts with any of
    the keys ``treedef`` / ``dtype`` / ``bucket`` / ``shape`` / ``plan`` /
    ``device`` holding hashable summaries of the respective signature aspect.
    Returns ``"initial"`` for the first compile ever, else the
    highest-priority field that differs from the NEAREST previous fingerprint
    (fewest differing fields) — the minimal change that forced the retrace.
    """
    if not previous:
        return "initial"
    best_diff: Optional[List[str]] = None
    for old in previous:
        diff = [k for k, _ in _CAUSE_BY_FIELD if new.get(k) != old.get(k)]
        if best_diff is None or len(diff) < len(best_diff):
            best_diff = diff
            if not diff:
                break
    if not best_diff:
        # identical fingerprint yet a new cache entry: something outside the
        # fingerprinted aspects changed (should not happen — surfaced, not hidden)
        return "unknown"
    causes = dict(_CAUSE_BY_FIELD)
    for field, _ in _CAUSE_BY_FIELD:
        if field in best_diff:
            return causes[field]
    return "unknown"
