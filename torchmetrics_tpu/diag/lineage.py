"""End-to-end value provenance & freshness plane.

Every queued/async/federated layer the engine grew (scan queues, background
drains, quarantine, degraded sync, cross-pod folds) widened the gap between
"steps the training loop handed us" and "steps the value you are looking at
actually reflects" — and until now no observed value could state that gap.
This module closes it host-side, from counters the engine already keeps:

- **Watermarks** — a monotonic per-owner ledger of steps *enqueued* (handed to
  a scan queue), steps *folded* (applied by a drain, replay, or discard
  realignment), and steps *observed* (reflected by the last observation), plus
  per-reason exclusion counts (``quarantined``, ``replayed``, ``discarded``).
- **Staleness bound** — at observation time, ``steps_enqueued - steps_folded``
  is the exact steps-behind bound, and the PR-5 profile-epoch clock
  (:func:`~torchmetrics_tpu.diag.profile.epoch_now_us`) dates the oldest
  still-unfolded enqueue for a wall-µs-behind bound. Zero device reads: both
  numbers come from host counters, so the plane is STRICT-guard clean by
  construction.
- **Causal spans** — a lineage id opened at enqueue rides ``_DrainWork``
  through the drain/join/sync/compute events (a ``lineage`` data key on the
  existing kinds — no new event kinds for the hot path) and is rendered as
  Chrome-trace flow arrows by :func:`~torchmetrics_tpu.diag.timeline.
  merge_timelines`; the :data:`LINEAGE_HEADER` header carries the stamp
  cross-pod on ``/state`` and ``/telemetry.bin`` envelopes.
- **Coverage attestation** — degraded-sync membership and federation/fleet
  pod coverage (members included, per-pod seqs, excluded ids with reasons)
  stamp the :class:`ValueProvenance` record, so a global value computed from
  3/4 pods says so.

Freshness feeds the PR-19 SLO engine through the ``staleness_steps`` /
``staleness_us`` histogram series (``tm_tpu_staleness_steps`` /
``tm_tpu_staleness_seconds`` families), and ``/healthz`` names the stalest
owner when the ``value-freshness`` objective breaches.

The plane is passive and default-ON (``TORCHMETRICS_TPU_LINEAGE=0`` turns it
off); with it off every note/observe call is an early-return no-op, so
unsampled paths are byte-identical.
"""

from __future__ import annotations

import json
import os
import threading
from collections import Counter
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Generator, List, Optional, Sequence, Tuple

from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.diag.profile import epoch_now_us


def _user_error(message: str) -> Exception:
    # lazy: ``utilities`` transitively initializes parallel/engine — importing
    # it at module level from a diag-package module re-enters the half-built
    # package when ``diag/__init__`` (or ``engine/scan``) pulls lineage in
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    return TorchMetricsUserError(message)

__all__ = [
    "LINEAGE_HEADER",
    "ValueProvenance",
    "decode_lineage_header",
    "encode_lineage_header",
    "lineage_context",
    "lineage_enabled",
    "lineage_snapshot",
    "note_coverage",
    "note_discarded",
    "note_enqueued",
    "note_excluded",
    "note_folded",
    "note_observed",
    "observe_all",
    "observe_metric",
    "open_span",
    "provenance_of",
    "reset_lineage",
    "settle_span",
    "stalest_owner",
    "take_span",
]

#: Cross-pod provenance stamp header on ``/state`` and ``/telemetry.bin``
#: envelopes (compact JSON; see :func:`encode_lineage_header`).
LINEAGE_HEADER = "X-TM-Lineage"

_LINEAGE_ENV_VAR = "TORCHMETRICS_TPU_LINEAGE"

#: Exclusion reasons the watermark ledger recognizes. Anything else at a
#: ``note_excluded`` call site is a programming error, surfaced loudly.
_EXCLUSION_REASONS = ("discarded", "quarantined", "replayed")


def lineage_enabled() -> bool:
    """The ONE recognized parser for ``TORCHMETRICS_TPU_LINEAGE`` (fail-loud).

    Unset / ``""`` / ``"1"`` / ``"on"`` = on (the default: provenance is
    passive and host-side, so there is no hot-loop cost to opt out of);
    ``"0"`` / ``"off"`` = off. Anything else fails loud — the PR-7 env
    contract: a typo must not silently disable the evidence surface. A
    :func:`lineage_context` override wins over the environment.
    """
    if _enabled_override is not None:
        return _enabled_override
    raw = os.environ.get(_LINEAGE_ENV_VAR, "").strip().lower()
    if raw in ("", "1", "on"):
        return True
    if raw in ("0", "off"):
        return False
    raise _user_error(
        f"Invalid {_LINEAGE_ENV_VAR}={raw!r}: expected unset/'1'/'on' to"
        " enable value provenance or '0'/'off' to disable it."
    )


_enabled_override: Optional[bool] = None


@contextmanager
def lineage_context(enabled: bool = True) -> Generator:
    """Scoped enable/disable override (tests/bench — no environment mutation)."""
    global _enabled_override
    prev = _enabled_override
    _enabled_override = bool(enabled)
    try:
        yield
    finally:
        _enabled_override = prev


# ------------------------------------------------------------------ ledger

class _Watermark:
    """Mutable per-owner watermark row (guarded by the module lock)."""

    __slots__ = (
        "enqueued", "folded", "observed", "excluded",
        "pending_since_us", "open_span_id", "last_span_id",
    )

    def __init__(self) -> None:
        self.enqueued = 0
        self.folded = 0
        self.observed = 0
        self.excluded: Counter = Counter()
        # epoch-µs instant of the oldest enqueue not yet folded; None while
        # fully caught up. This dates the wall-staleness BOUND: the observed
        # value is at most (now - pending_since_us) behind the newest enqueue.
        self.pending_since_us: Optional[float] = None
        self.open_span_id: Optional[int] = None
        self.last_span_id: Optional[int] = None


_lock = threading.Lock()
_watermarks: Dict[str, _Watermark] = {}
_coverage: Dict[str, Dict[str, Any]] = {}  # owner -> last coverage stamp
_span_counter = 0

# lazy: engine.stats imports diag.trace at module import, so a module-level
# import here would re-enter a partially-initialized diag package
_stats_obj: Optional[Any] = None


def _stats():
    global _stats_obj
    if _stats_obj is None:
        from torchmetrics_tpu.engine.stats import EngineStats

        _stats_obj = EngineStats("lineage")
    return _stats_obj


def _mark(owner: str) -> _Watermark:
    wm = _watermarks.get(owner)
    if wm is None:
        wm = _watermarks[owner] = _Watermark()
    return wm


@dataclass
class ValueProvenance:
    """What one observed value actually covers, and how stale it is.

    Attached to computed values (``metric._provenance``), snapshots
    (:class:`~torchmetrics_tpu.serve.snapshot.StateSnapshot.provenance`),
    envelope headers (:data:`LINEAGE_HEADER`), and the ``provenance``
    section of :func:`~torchmetrics_tpu.diag.telemetry.telemetry_snapshot`.
    """

    owner: str
    where: str  # observation site: "compute" | "snapshot" | "scrape" | ...
    steps_enqueued: int
    steps_folded: int
    steps_observed: int
    staleness_steps: int  # enqueued-but-unfolded steps the value excludes
    staleness_us: float  # wall-µs bound: age of the oldest unfolded enqueue
    excluded: Dict[str, int] = field(default_factory=dict)  # reason -> steps
    span: Optional[int] = None  # last settled causal span (flow-arrow id)
    coverage: Optional[Dict[str, Any]] = None  # sync/federation membership

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "owner": self.owner,
            "where": self.where,
            "steps_enqueued": self.steps_enqueued,
            "steps_folded": self.steps_folded,
            "steps_observed": self.steps_observed,
            "staleness_steps": self.staleness_steps,
            "staleness_us": self.staleness_us,
            # sorted: byte-stable JSON (header stamps must be deterministic)
            "excluded": {k: self.excluded[k] for k in sorted(self.excluded)},
        }
        if self.span is not None:
            out["span"] = self.span
        if self.coverage is not None:
            out["coverage"] = self.coverage
        return out


# ------------------------------------------------------------------ writes

def note_enqueued(owner: str, steps: int = 1, span: bool = True) -> None:
    """Advance the enqueue watermark: ``steps`` handed to a queue, not yet
    applied. Called under the scan queue's push lock — the module lock nests
    inside it (never the reverse; no lock-order cycle). ``span=True`` (the
    default) also opens the owner's causal span when none is open, so the
    single-metric enqueue path pays ONE lock acquisition; fused queues pass
    ``span=False`` per member and open one span on the queue owner instead."""
    if not lineage_enabled():
        return
    global _span_counter
    with _lock:
        wm = _mark(owner)
        if wm.pending_since_us is None:
            # going caught-up -> behind: this instant dates the wall bound
            wm.pending_since_us = epoch_now_us()
        if span and wm.open_span_id is None:
            _span_counter += 1
            wm.open_span_id = _span_counter
            _stats().lineage_spans += 1
        wm.enqueued += steps


def note_folded(owner: str, steps: int) -> None:
    """Advance the fold watermark: ``steps`` actually applied to state."""
    if not lineage_enabled():
        return
    with _lock:
        wm = _mark(owner)
        wm.folded += steps
        if wm.folded >= wm.enqueued:
            wm.pending_since_us = None  # caught up: no wall staleness


def note_excluded(owner: str, reason: str, steps: int) -> None:
    """Count ``steps`` the observed value does NOT cover, by reason."""
    if reason not in _EXCLUSION_REASONS:
        raise _user_error(
            f"Unknown lineage exclusion reason {reason!r}; expected one of"
            f" {_EXCLUSION_REASONS}."
        )
    if not lineage_enabled() or steps <= 0:
        return
    with _lock:
        _mark(owner).excluded[reason] += steps


def note_discarded(owner: str, steps: int) -> None:
    """Realign after ``discard()``: dropped steps will never fold, so they
    advance the fold watermark (they no longer make the value stale) AND
    count as a ``discarded`` exclusion (the value still doesn't cover them).
    """
    if not lineage_enabled() or steps <= 0:
        return
    with _lock:
        wm = _mark(owner)
        wm.folded += steps
        wm.excluded["discarded"] += steps
        if wm.folded >= wm.enqueued:
            wm.pending_since_us = None


# ------------------------------------------------------------------ spans

def open_span(owner: str) -> Optional[int]:
    """Open (or return the already-open) causal span for ``owner``.

    Called at the first enqueue of a drain generation; the id flows through
    ``_DrainWork`` to the drain/join events and the timeline's flow arrows.
    """
    if not lineage_enabled():
        return None
    global _span_counter
    with _lock:
        wm = _mark(owner)
        if wm.open_span_id is None:
            _span_counter += 1
            wm.open_span_id = _span_counter
            _stats().lineage_spans += 1
        return wm.open_span_id


def take_span(owner: str) -> Optional[int]:
    """Take the open span (queue swap: the generation is leaving the queue).

    The taken id is stamped on the in-flight work; the next enqueue opens a
    fresh span. Settles as ``last_span_id`` so observations can reference the
    most recent causal chain even after the work retired.
    """
    if not lineage_enabled():
        return None
    with _lock:
        wm = _mark(owner)
        span, wm.open_span_id = wm.open_span_id, None
        if span is not None:
            wm.last_span_id = span
        return span


def settle_span(owner: str, span: Optional[int]) -> None:
    """Record ``span`` as the owner's most recently completed causal chain."""
    if span is None or not lineage_enabled():
        return
    with _lock:
        _mark(owner).last_span_id = span


# ------------------------------------------------------------------ coverage

def note_coverage(
    owner: str,
    members: Sequence[Any],
    seqs: Optional[Dict[str, int]] = None,
    excluded: Sequence[Tuple[Any, str]] = (),
) -> Optional[Dict[str, Any]]:
    """Attest what a folded value covers: members in, members out, and why.

    Wired at the three fold sites — degraded packed sync (rank membership),
    federation fold (pod ids + snapshot seqs), fleet telemetry merge. The
    stamp is stored per owner (``provenance_of`` attaches it to later
    observations), recorded as a ``lineage.coverage`` event, and returned so
    fold sites can embed it in their own results.
    """
    if not lineage_enabled():
        return None
    stamp: Dict[str, Any] = {
        "members": [str(m) for m in members],
        "excluded": [{"id": str(pid), "reason": str(reason)} for pid, reason in excluded],
    }
    if seqs:
        stamp["seqs"] = {str(k): int(seqs[k]) for k in sorted(seqs)}
    stamp["complete"] = not stamp["excluded"]
    with _lock:
        _mark(owner)  # aggregation-tier owners fold without enqueuing; the
        # row makes their coverage visible in lineage_snapshot/provenance_of
        _coverage[owner] = stamp
        _stats().lineage_coverage_folds += 1
    _diag.record(
        "lineage.coverage",
        owner,
        members=",".join(stamp["members"]),
        excluded=",".join(f"{e['id']}:{e['reason']}" for e in stamp["excluded"]),
        complete=stamp["complete"],
    )
    return stamp


# ------------------------------------------------------------------ reads

def note_observed(
    owner: str,
    where: str,
    coverage: Optional[Dict[str, Any]] = None,
) -> Optional[ValueProvenance]:
    """Build the provenance record for one observation of ``owner``.

    Sets the observed watermark to the fold watermark (an observation reflects
    exactly what has folded), computes both staleness bounds host-side, feeds
    the freshness histograms/SLO, and records a ``lineage.observe`` event
    carrying the span id for timeline flow arrows.
    """
    if not lineage_enabled():
        return None
    with _lock:
        wm = _mark(owner)
        wm.observed = wm.folded
        behind = max(0, wm.enqueued - wm.folded)
        wall_us = 0.0
        if behind and wm.pending_since_us is not None:
            wall_us = max(0.0, epoch_now_us() - wm.pending_since_us)
        record = ValueProvenance(
            owner=owner,
            where=where,
            steps_enqueued=wm.enqueued,
            steps_folded=wm.folded,
            steps_observed=wm.observed,
            staleness_steps=behind,
            staleness_us=round(wall_us, 3),
            excluded=dict(wm.excluded),
            span=wm.last_span_id,
            coverage=coverage if coverage is not None else _coverage.get(owner),
        )
        _stats().lineage_records += 1
    # histograms feed the value-freshness SLO: unconditional like the sidecar
    # scrape-latency series (bounded by observation volume, not step volume)
    _hist.observe(owner, "lineage", "staleness_steps", float(behind))
    _hist.observe(owner, "lineage", "staleness_us", record.staleness_us)
    data: Dict[str, Any] = {
        "where": where,
        "enqueued": record.steps_enqueued,
        "folded": record.steps_folded,
        "staleness_steps": record.staleness_steps,
        "staleness_us": record.staleness_us,
    }
    if record.span is not None:
        data["lineage"] = record.span
    _diag.record("lineage.observe", owner, **data)
    return record


def observe_metric(metric: Any, where: str, coverage: Optional[Dict[str, Any]] = None):
    """Observe by metric instance: keys by ``type(metric).__name__`` (the
    owner string every stats/event/quarantine site already uses) and attaches
    the record as ``metric._provenance`` for callers of ``compute()``."""
    record = note_observed(type(metric).__name__, where, coverage=coverage)
    if record is not None:
        try:
            object.__setattr__(metric, "_provenance", record)
        except (AttributeError, TypeError):
            pass  # slotted/frozen metric: the record still exists in the ledger
    return record


def observe_all(where: str) -> List[ValueProvenance]:
    """Observe every owner with watermark activity (the scrape-flush path)."""
    if not lineage_enabled():
        return []
    with _lock:
        owners = sorted(_watermarks)
    return [r for r in (note_observed(o, where) for o in owners) if r is not None]


def provenance_of(owner: str) -> Optional[ValueProvenance]:
    """The current record for ``owner`` WITHOUT advancing the observed
    watermark or feeding histograms (pure read — report/dump surfaces)."""
    if not lineage_enabled():
        return None
    with _lock:
        wm = _watermarks.get(owner)
        if wm is None:
            return None
        behind = max(0, wm.enqueued - wm.folded)
        wall_us = 0.0
        if behind and wm.pending_since_us is not None:
            wall_us = max(0.0, epoch_now_us() - wm.pending_since_us)
        return ValueProvenance(
            owner=owner,
            where="read",
            steps_enqueued=wm.enqueued,
            steps_folded=wm.folded,
            steps_observed=wm.observed,
            staleness_steps=behind,
            staleness_us=round(wall_us, 3),
            excluded=dict(wm.excluded),
            span=wm.last_span_id,
            coverage=_coverage.get(owner),
        )


def stalest_owner() -> Optional[Tuple[str, int, float]]:
    """``(owner, steps_behind, wall_us_behind)`` for the most stale owner, or
    ``None`` when every owner is caught up — the ``/healthz`` 503 detail."""
    if not lineage_enabled():
        return None
    worst: Optional[Tuple[str, int, float]] = None
    now = epoch_now_us()
    with _lock:
        for owner in sorted(_watermarks):
            wm = _watermarks[owner]
            behind = max(0, wm.enqueued - wm.folded)
            if behind <= 0:
                continue
            wall = max(0.0, now - wm.pending_since_us) if wm.pending_since_us is not None else 0.0
            if worst is None or (behind, wall) > (worst[1], worst[2]):
                worst = (owner, behind, round(wall, 3))
    return worst


def lineage_snapshot() -> Dict[str, Any]:
    """The whole ledger as a deterministic dict (telemetry/report/dump)."""
    if not lineage_enabled():
        return {"enabled": False, "owners": {}}
    with _lock:
        owners = sorted(_watermarks)
    rows = {}
    for owner in owners:
        record = provenance_of(owner)
        if record is not None:
            rows[owner] = record.as_dict()
    return {"enabled": True, "owners": rows}


# ------------------------------------------------------------------ headers

def encode_lineage_header(records: Sequence[Any]) -> str:
    """Compact single-line JSON for the :data:`LINEAGE_HEADER` stamp.

    Accepts :class:`ValueProvenance` records or their ``as_dict()`` form (the
    snapshot path carries the dict). One object per owner, sorted by owner,
    separators tightened — the same bytes for the same ledger state, so
    envelope tests can assert equality.
    """
    rows = sorted(
        (r.as_dict() if isinstance(r, ValueProvenance) else dict(r) for r in records),
        key=lambda d: d["owner"],
    )
    return json.dumps(rows, separators=(",", ":"), sort_keys=True)


def decode_lineage_header(text: str) -> List[Dict[str, Any]]:
    """Parse a :data:`LINEAGE_HEADER` stamp (ingest side; fail-loud)."""
    rows = json.loads(text)
    if not isinstance(rows, list):
        raise _user_error(
            f"{LINEAGE_HEADER} header must be a JSON list of provenance rows,"
            f" got {type(rows).__name__}."
        )
    return rows


# ------------------------------------------------------------------ reset

def reset_lineage() -> None:
    """Drop every watermark, span, and coverage stamp (lockstep with
    :func:`~torchmetrics_tpu.engine.stats.reset_engine_stats` — a stale
    watermark would attribute the previous scenario's backlog to the fresh
    run as phantom staleness)."""
    global _span_counter
    with _lock:
        _watermarks.clear()
        _coverage.clear()
        _span_counter = 0
