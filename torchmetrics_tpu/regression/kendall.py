"""Modular KendallRankCorrCoef (reference ``src/torchmetrics/regression/kendall.py``).

Raw values in cat list states; the O(n²) vectorized pair counting runs in compute.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax

from torchmetrics_tpu.functional.regression.kendall import (
    _kendall_corrcoef_compute,
    _kendall_corrcoef_update,
    _MetricVariant,
    _TestAlternative,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class KendallRankCorrCoef(Metric):
    """Kendall's tau (reference ``kendall.py:36-171``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.regression.kendall import KendallRankCorrCoef
        >>> metric = KendallRankCorrCoef()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    preds: List[Array]
    target: List[Array]

    def __init__(
        self,
        variant: str = "b",
        t_test: bool = False,
        alternative: Optional[str] = "two-sided",
        num_outputs: int = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(t_test, bool):
            raise ValueError(f"Argument `t_test` is expected to be of a type `bool`, but got {type(t_test)}.")
        if t_test and alternative is None:
            raise ValueError("Argument `alternative` is required if `t_test=True` but got `None`.")
        self.variant = _MetricVariant.from_str(str(variant))
        self.alternative = _TestAlternative.from_str(str(alternative)) if t_test else None
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append one batch of raw values."""
        preds, target = _kendall_corrcoef_update(preds, target, self.num_outputs)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Tau (and p-value if ``t_test``) over the full stream."""
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        tau, p_value = _kendall_corrcoef_compute(preds, target, self.variant, self.alternative)
        if p_value is not None:
            return tau, p_value
        return tau

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
