"""Modular SpearmanCorrCoef (reference ``src/torchmetrics/regression/spearman.py``).

Raw values kept in cat list states; ranking (needs the full sequence) runs in compute.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from torchmetrics_tpu.functional.regression.spearman import (
    _spearman_corrcoef_compute,
    _spearman_corrcoef_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class SpearmanCorrCoef(Metric):
    """Spearman ρ (reference ``spearman.py:25-112``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SpearmanCorrCoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> metric = SpearmanCorrCoef()
        >>> print(round(float(metric(preds, target)), 4))
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    preds: List[Array]
    target: List[Array]

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append one batch of raw values."""
        preds, target = _spearman_corrcoef_update(preds, target, self.num_outputs)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Rank the full stream and correlate."""
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
