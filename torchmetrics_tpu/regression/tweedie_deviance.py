"""Modular TweedieDevianceScore (reference ``src/torchmetrics/regression/tweedie_deviance.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.tweedie_deviance import (
    _tweedie_deviance_score_compute,
    _tweedie_deviance_score_update,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class TweedieDevianceScore(Metric):
    """Tweedie deviance (reference ``tweedie_deviance.py:25-115``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.regression.tweedie_deviance import TweedieDevianceScore
        >>> metric = TweedieDevianceScore(power=1.5)
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.112
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, power: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power
        self.add_state("sum_deviance_score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num_observations", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, targets: Array) -> None:
        """Accumulate deviance sum and count."""
        sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, self.power)
        self.sum_deviance_score = self.sum_deviance_score + sum_deviance_score
        self.num_observations = self.num_observations + num_observations

    def compute(self) -> Array:
        """Mean deviance."""
        return _tweedie_deviance_score_compute(self.sum_deviance_score, self.num_observations)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
