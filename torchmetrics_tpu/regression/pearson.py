"""Modular PearsonCorrCoef (reference ``src/torchmetrics/regression/pearson.py``).

The canonical ``dist_reduce_fx=None`` metric: per-chip streaming moments are gathered
*raw* (stacked, never pre-reduced) and merged with the pairwise-moment algorithm
``_final_aggregation`` at compute — exactly the reference's ``pearson.py:28-70,135-140``
semantics, with the merge promoted to ``functional/regression/pearson.py`` so
``merge_state`` and the sync path share one implementation.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.pearson import (
    _final_aggregation,
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class PearsonCorrCoef(Metric):
    """Pearson r from streaming moments (reference ``pearson.py:72-163``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import PearsonCorrCoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> pearson = PearsonCorrCoef()
        >>> print(round(float(pearson(preds, target)), 4))
        0.9849
    """

    is_differentiable: bool = True
    higher_is_better: Optional[bool] = None  # both +1 and -1 are "good"
    full_state_update: bool = True
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("mean_x", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("mean_y", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("var_x", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("var_y", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("corr_xy", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("n_total", jnp.zeros(num_outputs), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        """One streaming-moment step."""
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds,
            target,
            self.mean_x,
            self.mean_y,
            self.var_x,
            self.var_y,
            self.corr_xy,
            self.n_total,
            self.num_outputs,
        )

    def _merged_moments(self) -> tuple:
        """States as one set of moments, folding raw gathered per-chip rows if present.

        After a ``dist_reduce_fx=None`` sync (or ``merge_state``) each state is stacked
        to ``(world, num_outputs)``; detect that and run ``_final_aggregation``.
        Shared by :class:`ConcordanceCorrCoef`.
        """
        if (self.num_outputs == 1 and self.mean_x.size > 1) or (self.num_outputs > 1 and self.mean_x.ndim > 1):
            return _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        return self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total

    def compute(self) -> Array:
        """Correlation; merges raw gathered per-chip moments first when present."""
        _, _, var_x, var_y, corr_xy, n_total = self._merged_moments()
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
