"""Modular RelativeSquaredError (reference ``src/torchmetrics/regression/rse.py``).

Subclasses :class:`R2Score`: identical moment states (Σy², Σy, RSS, n), only the final
formula differs — which also lets MetricCollection put both in one compute group.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.functional.regression.rse import _relative_squared_error_compute
from torchmetrics_tpu.regression.r2 import R2Score

Array = jax.Array


class RelativeSquaredError(R2Score):
    """RSE (reference ``rse.py:24-105``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.regression.rse import RelativeSquaredError
        >>> metric = RelativeSquaredError()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.0514
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: Optional[float] = None

    def __init__(self, num_outputs: int = 1, squared: bool = True, **kwargs: Any) -> None:
        super().__init__(num_outputs=num_outputs, **kwargs)
        self.squared = squared

    def compute(self) -> Array:
        """Relative squared error."""
        return _relative_squared_error_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, squared=self.squared
        )

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
