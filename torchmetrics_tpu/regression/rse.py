"""Modular RelativeSquaredError (reference ``src/torchmetrics/regression/rse.py``).

Shares the R² moment states (Σy², Σy, RSS, n).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.r2 import _r2_score_update
from torchmetrics_tpu.functional.regression.rse import _relative_squared_error_compute
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class RelativeSquaredError(Metric):
    """RSE (reference ``rse.py:24-105``)."""

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, num_outputs: int = 1, squared: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        self.add_state("sum_squared_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("sum_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("residual", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")
        self.squared = squared

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate Σy², Σy, RSS, n."""
        sum_squared_obs, sum_obs, rss, n_obs = _r2_score_update(preds, target)
        self.sum_squared_error = self.sum_squared_error + sum_squared_obs
        self.sum_error = self.sum_error + sum_obs
        self.residual = self.residual + rss
        self.total = self.total + n_obs

    def compute(self) -> Array:
        """Relative squared error."""
        return _relative_squared_error_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, squared=self.squared
        )

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
