"""Modular MeanSquaredLogError (reference ``src/torchmetrics/regression/log_mse.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.log_mse import (
    _mean_squared_log_error_compute,
    _mean_squared_log_error_update,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class MeanSquaredLogError(Metric):
    """MSLE (reference ``log_mse.py:26-95``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.regression.log_mse import MeanSquaredLogError
        >>> metric = MeanSquaredLogError()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.0286
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate squared log error and count."""
        sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
        self.sum_squared_log_error = self.sum_squared_log_error + sum_squared_log_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        """Mean squared log error."""
        return _mean_squared_log_error_compute(self.sum_squared_log_error, self.total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
