"""Modular MAPE / SMAPE / WMAPE (reference ``src/torchmetrics/regression/{mape,symmetric_mape,wmape}.py``).

All three are plain sum-counter states; kept in one module, exported separately.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.mape import (
    _mean_absolute_percentage_error_compute,
    _mean_absolute_percentage_error_update,
)
from torchmetrics_tpu.functional.regression.symmetric_mape import (
    _symmetric_mean_absolute_percentage_error_compute,
    _symmetric_mean_absolute_percentage_error_update,
)
from torchmetrics_tpu.functional.regression.wmape import (
    _weighted_mean_absolute_percentage_error_compute,
    _weighted_mean_absolute_percentage_error_update,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class MeanAbsolutePercentageError(Metric):
    """MAPE (reference ``mape.py:26-102``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.regression.mape import MeanAbsolutePercentageError
        >>> metric = MeanAbsolutePercentageError()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.3274
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate |err/target| and count."""
        sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + sum_abs_per_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        """Mean absolute percentage error."""
        return _mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class SymmetricMeanAbsolutePercentageError(Metric):
    """SMAPE (reference ``symmetric_mape.py:26-101``)."""

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 2.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate symmetric percentage error and count."""
        sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + sum_abs_per_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        """Symmetric mean absolute percentage error."""
        return _symmetric_mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class WeightedMeanAbsolutePercentageError(Metric):
    """WMAPE (reference ``wmape.py:25-96``)."""

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_scale", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate |err| and |target| sums."""
        sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.sum_scale = self.sum_scale + sum_scale

    def compute(self) -> Array:
        """Weighted mean absolute percentage error."""
        return _weighted_mean_absolute_percentage_error_compute(self.sum_abs_error, self.sum_scale)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
