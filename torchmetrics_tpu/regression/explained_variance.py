"""Modular ExplainedVariance (reference ``src/torchmetrics/regression/explained_variance.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.explained_variance import (
    ALLOWED_MULTIOUTPUT,
    _explained_variance_compute,
    _explained_variance_update,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class ExplainedVariance(Metric):
    """Explained variance from streaming sums (reference ``explained_variance.py:26-125``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ExplainedVariance
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> metric = ExplainedVariance()
        >>> print(round(float(metric(preds, target)), 4))
        0.9572
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if multioutput not in ALLOWED_MULTIOUTPUT:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {ALLOWED_MULTIOUTPUT}"
            )
        self.multioutput = multioutput
        self.add_state("sum_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_target", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_obs", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate the five moment sums."""
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(
            preds, target
        )
        self.n_obs = self.n_obs + n_obs
        self.sum_error = self.sum_error + sum_error
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.sum_target = self.sum_target + sum_target
        self.sum_squared_target = self.sum_squared_target + sum_squared_target

    def compute(self) -> Array:
        """Explained variance under the chosen multioutput reduction."""
        return _explained_variance_compute(
            self.n_obs,
            self.sum_error,
            self.sum_squared_error,
            self.sum_target,
            self.sum_squared_target,
            self.multioutput,
        )

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
