"""Modular LogCoshError (reference ``src/torchmetrics/regression/log_cosh.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.log_cosh import _log_cosh_error_compute, _log_cosh_error_update
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class LogCoshError(Metric):
    """Log-cosh error (reference ``log_cosh.py:25-109``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.regression.log_cosh import LogCoshError
        >>> metric = LogCoshError()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.1685
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_log_cosh_error", jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(1), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate log-cosh error and count."""
        sum_log_cosh_error, n_obs = _log_cosh_error_update(preds, target, self.num_outputs)
        self.sum_log_cosh_error = self.sum_log_cosh_error + sum_log_cosh_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        """Mean log-cosh error."""
        return _log_cosh_error_compute(self.sum_log_cosh_error, self.total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
