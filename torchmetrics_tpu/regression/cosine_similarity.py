"""Modular CosineSimilarity (reference ``src/torchmetrics/regression/cosine_similarity.py``).

List (cat) states — whole rows kept until compute, gathered with one all_gather.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from torchmetrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class CosineSimilarity(Metric):
    """Row-wise cosine similarity (reference ``cosine_similarity.py:25-96``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        >>> target = jnp.asarray([[1.0, 2.5], [2.5, 4.0], [5.5, 6.5]])
        >>> from torchmetrics_tpu.regression.cosine_similarity import CosineSimilarity
        >>> metric = CosineSimilarity()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        2.9929
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds: List[Array]
    target: List[Array]

    def __init__(self, reduction: str = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append one batch of rows."""
        preds, target = _cosine_similarity_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Cosine similarity under the chosen reduction."""
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
