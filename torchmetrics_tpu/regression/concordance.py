"""Modular ConcordanceCorrCoef (reference ``src/torchmetrics/regression/concordance.py``).

Subclasses PearsonCorrCoef: identical moment states (and raw-gather merge), only the
final formula differs — which also lets MetricCollection put both in one compute group.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.functional.regression.concordance import _concordance_corrcoef_compute
from torchmetrics_tpu.regression.pearson import PearsonCorrCoef

Array = jax.Array


class ConcordanceCorrCoef(PearsonCorrCoef):
    """CCC from the Pearson moment states (reference ``concordance.py:19-100``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.regression.concordance import ConcordanceCorrCoef
        >>> metric = ConcordanceCorrCoef()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.9777
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        """Concordance correlation; merges raw gathered per-chip moments first."""
        return _concordance_corrcoef_compute(*self._merged_moments())

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
