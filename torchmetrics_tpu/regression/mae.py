"""Modular MeanAbsoluteError (reference ``src/torchmetrics/regression/mae.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.mae import _mean_absolute_error_compute, _mean_absolute_error_update
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class MeanAbsoluteError(Metric):
    """MAE (reference ``mae.py:26-98``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanAbsoluteError
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> metric = MeanAbsoluteError()
        >>> print(float(metric(preds, target)))
        0.5
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate absolute error and count."""
        sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        """Mean absolute error."""
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
