"""Modular MinkowskiDistance (reference ``src/torchmetrics/regression/minkowski.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.minkowski import (
    _minkowski_distance_compute,
    _minkowski_distance_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

Array = jax.Array


class MinkowskiDistance(Metric):
    """Minkowski distance of order p (reference ``minkowski.py:25-102``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.regression.minkowski import MinkowskiDistance
        >>> metric = MinkowskiDistance(p=3.0)
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        1.0772
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, p: float, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(p, (float, int)) and p >= 1):
            raise TorchMetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {p}")
        self.p = p
        self.add_state("minkowski_dist_sum", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, targets: Array) -> None:
        """Accumulate Σ|err|^p."""
        minkowski_dist_sum = _minkowski_distance_update(preds, targets, self.p)
        self.minkowski_dist_sum = self.minkowski_dist_sum + minkowski_dist_sum

    def compute(self) -> Array:
        """p-th root of the accumulated sum."""
        return _minkowski_distance_compute(self.minkowski_dist_sum, self.p)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
