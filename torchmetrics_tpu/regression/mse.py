"""Modular MeanSquaredError (reference ``src/torchmetrics/regression/mse.py``).

Sum-counter state — one psum at sync, jit-compiled update.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.mse import _mean_squared_error_compute, _mean_squared_error_update
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class MeanSquaredError(Metric):
    """MSE / RMSE (reference ``mse.py:26-120``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanSquaredError
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> mean_squared_error = MeanSquaredError()
        >>> print(float(mean_squared_error(preds, target)))
        0.875
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, squared: bool = True, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(squared, bool):
            raise ValueError(f"Expected argument `squared` to be a boolean but got {squared}")
        self.squared = squared
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_squared_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate squared error and count."""
        sum_squared_error, n_obs = _mean_squared_error_update(preds, target, num_outputs=self.num_outputs)
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        """Mean (root) squared error."""
        return _mean_squared_error_compute(self.sum_squared_error, self.total, squared=self.squared)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
