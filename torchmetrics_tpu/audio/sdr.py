"""Modular SDR metrics (reference ``audio/sdr.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.audio._mean_base import _MeanOfBatchValues
from torchmetrics_tpu.functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
)

Array = jax.Array


class SignalDistortionRatio(_MeanOfBatchValues):
    """Average SDR (reference ``sdr.py:29-162``)."""

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag

    def update(self, preds: Array, target: Array) -> None:
        self._update_from_values(
            signal_distortion_ratio(
                preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
            )
        )


class ScaleInvariantSignalDistortionRatio(_MeanOfBatchValues):
    """Average SI-SDR (reference ``sdr.py:163-246``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalDistortionRatio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> si_sdr = ScaleInvariantSignalDistortionRatio()
        >>> print(round(float(si_sdr(preds, target)), 4))
        18.403
    """

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def update(self, preds: Array, target: Array) -> None:
        self._update_from_values(
            scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        )
