"""Modular STOI (reference ``audio/stoi.py:29-157``)."""

from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.audio._mean_base import _MeanOfBatchValues
from torchmetrics_tpu.functional.audio.stoi import short_time_objective_intelligibility
from torchmetrics_tpu.utilities.imports import _PYSTOI_AVAILABLE

Array = jax.Array


class ShortTimeObjectiveIntelligibility(_MeanOfBatchValues):
    """Average STOI via the external ``pystoi`` package (host DSP, as in the reference)."""

    is_differentiable = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PYSTOI_AVAILABLE:
            raise ModuleNotFoundError(
                "ShortTimeObjectiveIntelligibility metric requires that `pystoi` is installed."
                " Either install as `pip install torchmetrics[audio]` or `pip install pystoi`."
            )
        self.fs = fs
        self.extended = extended

    def update(self, preds: Array, target: Array) -> None:
        self._update_from_values(short_time_objective_intelligibility(preds, target, self.fs, self.extended, False))
