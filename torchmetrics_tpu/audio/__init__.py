"""Audio metrics (reference ``src/torchmetrics/audio/__init__.py``)."""

from torchmetrics_tpu.audio.pit import PermutationInvariantTraining
from torchmetrics_tpu.audio.sdr import ScaleInvariantSignalDistortionRatio, SignalDistortionRatio
from torchmetrics_tpu.audio.snr import (
    ComplexScaleInvariantSignalNoiseRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalNoiseRatio,
)
from torchmetrics_tpu.utilities.imports import _PESQ_AVAILABLE, _PYSTOI_AVAILABLE

__all__ = [
    "ComplexScaleInvariantSignalNoiseRatio",
    "PermutationInvariantTraining",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
]

if _PESQ_AVAILABLE:
    from torchmetrics_tpu.audio.pesq import PerceptualEvaluationSpeechQuality  # noqa: F401

    __all__.append("PerceptualEvaluationSpeechQuality")

if _PYSTOI_AVAILABLE:
    from torchmetrics_tpu.audio.stoi import ShortTimeObjectiveIntelligibility  # noqa: F401

    __all__.append("ShortTimeObjectiveIntelligibility")
