"""Modular PermutationInvariantTraining (reference ``audio/pit.py:30-147``)."""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax

from torchmetrics_tpu.audio._mean_base import _MeanOfBatchValues
from torchmetrics_tpu.functional.audio.pit import permutation_invariant_training

Array = jax.Array


class PermutationInvariantTraining(_MeanOfBatchValues):
    """Average best-permutation metric value; extra kwargs flow to ``metric_func``."""

    def __init__(
        self,
        metric_func: Callable,
        mode: str = "speaker-wise",
        eval_func: str = "max",
        **kwargs: Any,
    ) -> None:
        # route every kernel Metric option to the base; the rest feed metric_func
        _metric_option_names = (
            "compute_on_cpu",
            "dist_sync_on_step",
            "process_group",
            "dist_sync_fn",
            "distributed_available_fn",
            "sync_on_compute",
            "compute_with_cache",
        )
        base_kwargs: Dict[str, Any] = {
            name: kwargs.pop(name) for name in _metric_option_names if name in kwargs
        }
        super().__init__(**base_kwargs)
        self.metric_func = metric_func
        self.mode = mode
        self.eval_func = eval_func
        self.kwargs = kwargs

    def update(self, preds: Array, target: Array) -> None:
        best_metric = permutation_invariant_training(
            preds, target, self.metric_func, self.mode, self.eval_func, **self.kwargs
        )[0]
        self._update_from_values(best_metric)
