"""Modular PESQ (reference ``audio/pesq.py:29-167``)."""

from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.audio._mean_base import _MeanOfBatchValues
from torchmetrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality
from torchmetrics_tpu.utilities.imports import _PESQ_AVAILABLE

Array = jax.Array


class PerceptualEvaluationSpeechQuality(_MeanOfBatchValues):
    """Average PESQ via the external ``pesq`` package (host DSP, as in the reference)."""

    is_differentiable = False
    plot_lower_bound = -0.5
    plot_upper_bound = 4.5

    def __init__(self, fs: int, mode: str, n_processes: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PESQ_AVAILABLE:
            raise ModuleNotFoundError(
                "PerceptualEvaluationSpeechQuality metric requires that `pesq` is installed."
                " Either install as `pip install torchmetrics[audio]` or `pip install pesq`."
            )
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        self.mode = mode
        self.n_processes = n_processes

    def update(self, preds: Array, target: Array) -> None:
        self._update_from_values(
            perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode, False, self.n_processes)
        )
