"""Shared running-mean base for the audio metrics.

Every reference audio modular metric keeps the same two sum states and averages at
compute (``audio/snr.py:86-98``, ``sdr.py:107-121``, ``pit.py:101-115``); this base
holds that pattern once.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric

Array = jax.Array


class _MeanOfBatchValues(Metric):
    """Accumulate ``value.sum()`` / ``value.size`` sum states and average at compute."""

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False

    sum_value: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_value", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update_from_values(self, values: Array) -> None:
        self.sum_value = self.sum_value + values.sum()
        self.total = self.total + values.size

    def compute(self) -> Array:
        """Average over every element seen."""
        return self.sum_value / self.total

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
