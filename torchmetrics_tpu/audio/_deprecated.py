"""Deprecated-root-import shims (reference ``audio/_deprecated.py``)."""

from torchmetrics_tpu.audio import (
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from torchmetrics_tpu.utilities.deprecation import root_alias

_PermutationInvariantTraining = root_alias(PermutationInvariantTraining, "audio")
_ScaleInvariantSignalDistortionRatio = root_alias(ScaleInvariantSignalDistortionRatio, "audio")
_ScaleInvariantSignalNoiseRatio = root_alias(ScaleInvariantSignalNoiseRatio, "audio")
_SignalDistortionRatio = root_alias(SignalDistortionRatio, "audio")
_SignalNoiseRatio = root_alias(SignalNoiseRatio, "audio")
