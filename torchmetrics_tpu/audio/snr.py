"""Modular SNR metrics (reference ``audio/snr.py``)."""

from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.audio._mean_base import _MeanOfBatchValues
from torchmetrics_tpu.functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)

Array = jax.Array


class SignalNoiseRatio(_MeanOfBatchValues):
    """Average SNR over all seen samples (reference ``snr.py:35-139``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import SignalNoiseRatio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> snr = SignalNoiseRatio()
        >>> print(round(float(snr(preds, target)), 4))
        16.1805
    """

    plot_lower_bound = None
    plot_upper_bound = None

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def update(self, preds: Array, target: Array) -> None:
        self._update_from_values(signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean))


class ScaleInvariantSignalNoiseRatio(_MeanOfBatchValues):
    """Average SI-SNR (reference ``snr.py:142-237``)."""

    def update(self, preds: Array, target: Array) -> None:
        self._update_from_values(scale_invariant_signal_noise_ratio(preds=preds, target=target))


class ComplexScaleInvariantSignalNoiseRatio(_MeanOfBatchValues):
    """Average C-SI-SNR (reference ``snr.py:239-330``).
    """

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def update(self, preds: Array, target: Array) -> None:
        self._update_from_values(
            complex_scale_invariant_signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        )
