__version__ = "1.0.0rc0"
__author__ = "torchmetrics-tpu contributors"
__license__ = "Apache-2.0"
__docs__ = "TPU-native (JAX/XLA) metrics framework with torchmetrics capability parity"

__all__ = ["__author__", "__docs__", "__license__", "__version__"]
