"""Classwise output dict wrapper (reference ``wrappers/classwise.py:26``)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from torchmetrics_tpu.metric import Metric

Array = jax.Array


class ClasswiseWrapper(Metric):
    """Split a per-class metric output into a labeled dict (reference ``classwise.py:26``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ClasswiseWrapper
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None), labels=["a", "b", "c"])
        >>> out = metric(jnp.asarray([0, 1, 2, 0]), jnp.asarray([0, 1, 1, 0]))
        >>> {k: round(float(v), 2) for k, v in sorted(out.items())}
        {'multiclassaccuracy_a': 1.0, 'multiclassaccuracy_b': 0.5, 'multiclassaccuracy_c': 0.0}
    """

    def __init__(self, metric: Metric, labels: Optional[List[str]] = None) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `torchmetrics_tpu.Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.metric = metric
        self.labels = labels
        self._update_count = 1

    def _convert(self, x: Array) -> Dict[str, Array]:
        name = self.metric.__class__.__name__.lower()
        if self.labels is None:
            return {f"{name}_{i}": val for i, val in enumerate(x)}
        return {f"{name}_{lab}": val for lab, val in zip(self.labels, x)}

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Batch value as labeled dict."""
        return self._convert(self.metric(*args, **kwargs))

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Forward to the wrapped metric."""
        self.metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        """Final value as labeled dict."""
        return self._convert(self.metric.compute())

    def reset(self) -> None:
        """Reset the wrapped metric."""
        self.metric.reset()

    def _wrap_update(self, update: Any) -> Any:
        return update

    def _wrap_compute(self, compute: Any) -> Any:
        return compute
