"""Running-window wrapper.

Capability parity: reference ``src/torchmetrics/wrappers/running.py:26-130``: duplicates
each base-metric state ``window`` times as ``key_{i}`` ring slots; ``compute`` folds all
slots back into the base metric via ``_reduce_states`` (the merge primitive).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax

from torchmetrics_tpu.metric import Metric

Array = jax.Array


class Running(Metric):
    """Compute a metric over a fixed running window of recent updates (reference ``running.py:26``).

    ``forward`` still returns the current-batch value; ``compute`` returns the windowed
    value. Memory grows linearly with ``window`` (one state copy per slot), and every
    ``update`` snapshots the FULL base state into its ring slot on the host path —
    exact per-update granularity at O(window) state copies. For unbounded serving
    streams prefer :class:`torchmetrics_tpu.serve.window.WindowedMetric`: a device-
    resident ring of ``buckets`` partial states whose advance/evict/fold compiles
    into one donated engine dispatch per step (bucketed granularity, O(buckets)
    memory, no per-step host attribute traffic).

    ``reset`` rewinds the ring cursor (``_num_vals_seen``) with the states — a reset
    instance is indistinguishable from a fresh one (a stale cursor would silently
    resume mid-ring and fold new slots against evicted positions); pinned by
    ``tests/test_serve.py::TestRunningResetRegression``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import Running, SumMetric
        >>> metric = Running(SumMetric(), window=3)
        >>> for v in (1.0, 2.0, 3.0, 4.0):
        ...     _ = metric(jnp.asarray(v))
        >>> float(metric.compute())  # sum over the trailing window {2, 3, 4}
        9.0
    """

    def __init__(self, base_metric: Metric, window: int = 5) -> None:
        super().__init__()
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected argument `metric` to be an instance of `torchmetrics_tpu.Metric` but got {base_metric}"
            )
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        self.base_metric = base_metric
        self.window = window
        if base_metric.full_state_update is not False:
            raise ValueError(
                f"Expected attribute `full_state_update` set to `False` but got {base_metric.full_state_update}"
            )
        self._num_vals_seen = 0

        for key in base_metric._defaults:
            for i in range(window):
                self.add_state(
                    name=key + f"_{i}", default=base_metric._defaults[key], dist_reduce_fx=base_metric._reductions[key]
                )

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the underlying metric, then snapshot its state into the current ring slot."""
        val = self._num_vals_seen % self.window
        self.base_metric.update(*args, **kwargs)
        # the raw getattr below is a state OBSERVATION the scan queue cannot
        # see (engine/scan.py staleness contract): with multi-step scan on,
        # the inner update may only be ENQUEUED — drain it first, or the slot
        # snapshots default state and the reset() would discard the payload
        self.base_metric._drain_scan("observation:running-slot")
        for key in self.base_metric._defaults:
            setattr(self, key + f"_{val}", getattr(self.base_metric, key))
        self.base_metric.reset()
        self._num_vals_seen += 1

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Forward to the underlying metric (batch value), then snapshot the slot."""
        val = self._num_vals_seen % self.window
        res = self.base_metric.forward(*args, **kwargs)
        for key in self.base_metric._defaults:
            setattr(self, key + f"_{val}", getattr(self.base_metric, key))
        self.base_metric.reset()
        self._num_vals_seen += 1
        # this override bypasses the wrapped update(), so bump the wrapper's own
        # count — otherwise compute() after forward-only use warns "before update"
        self._update_count += 1
        self._computed = None
        return res

    def compute(self) -> Any:
        """Fold the occupied window slots into the base metric and compute.

        Reference ``running.py:118-126`` folds with ``_reduce_states``, which breaks
        mean-reduced states (the reset base metric has ``_update_count == 0``). Folding
        with ``merge_state(..., incoming_count=1)`` instead — each slot snapshots
        exactly one update — weights every reduction correctly, and skipping the
        never-written slots keeps defaults out of mean/max/min states.
        """
        for i in range(min(self._num_vals_seen, self.window)):
            self.base_metric.merge_state(
                {key: getattr(self, key + f"_{i}") for key in self.base_metric._defaults},
                incoming_count=1,
            )
        val = self.base_metric.compute()
        self.base_metric.reset()
        return val

    def reset(self) -> None:
        """Reset the ring and the base metric."""
        super().reset()
        self.base_metric.reset()
        self._num_vals_seen = 0

    def plot(
        self, val: Optional[Union[Array, Sequence[Array]]] = None, ax: Optional[Any] = None
    ) -> Any:
        return self._plot(val, ax)
