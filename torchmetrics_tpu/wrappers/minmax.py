"""Min/max tracking wrapper (reference ``wrappers/minmax.py:28``)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric

Array = jax.Array


class MinMaxMetric(Metric):
    """Track min/max of a scalar metric across compute calls (reference ``minmax.py:28``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MinMaxMetric
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metric = MinMaxMetric(BinaryAccuracy())
        >>> _ = metric(jnp.asarray([1.0, 0.0, 1.0]), jnp.asarray([1, 0, 0]))
        >>> _ = metric(jnp.asarray([1.0, 0.0, 1.0]), jnp.asarray([1, 0, 1]))
        >>> print({k: round(float(v), 4) for k, v in sorted(metric.compute().items())})
        {'max': 1.0, 'min': 0.6667, 'raw': 1.0}
    """

    full_state_update: Optional[bool] = True
    min_val: Array
    max_val: Array

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `torchmetrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.min_val = jnp.asarray(float("inf"))
        self.max_val = jnp.asarray(float("-inf"))

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the underlying metric."""
        self._base_metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        """{'raw', 'min', 'max'}; min/max updated here (reference ``minmax.py``)."""
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}.")
        self.max_val = jnp.where(self.max_val < val, val, self.max_val)
        self.min_val = jnp.where(self.min_val > val, val, self.min_val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        """Reset the underlying metric — NOT the min/max trackers.

        Reference parity (verified by executing ``wrappers/minmax.py:28`` side by
        side): ``min_val``/``max_val`` are plain attributes, not registered states,
        so the reference's ``reset`` leaves them untouched. This also makes the
        full-state ``forward`` path track per-batch extrema across steps (the
        mid-forward ``reset()`` must not clear them).
        """
        super().reset()
        self._base_metric.reset()

    @staticmethod
    def _is_suitable_val(val: Union[float, Array]) -> bool:
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, (jnp.ndarray, jax.Array)) and not isinstance(val, (list, tuple)):
            return val.size == 1
        return False

    def plot(self, val: Optional[Union[Array, Sequence[Array]]] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
