"""Multitask wrapper (reference ``wrappers/multitask.py:28``)."""

from __future__ import annotations

from typing import Any, Dict, Union

import jax

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class MultitaskWrapper(Metric):
    """Different metrics on different tasks via dict inputs (reference ``multitask.py:28``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanSquaredError, MultitaskWrapper
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metric = MultitaskWrapper({"cls": BinaryAccuracy(), "reg": MeanSquaredError()})
        >>> metric.update(
        ...     {"cls": jnp.asarray([1.0, 0.0, 1.0, 1.0]), "reg": jnp.asarray([1.0, 2.0])},
        ...     {"cls": jnp.asarray([1, 0, 0, 1]), "reg": jnp.asarray([1.0, 4.0])},
        ... )
        >>> {k: round(float(v), 2) for k, v in sorted(metric.compute().items())}
        {'cls': 0.75, 'reg': 2.0}
    """

    is_differentiable = False

    def __init__(self, task_metrics: Dict[str, Union[Metric, MetricCollection]]) -> None:
        self._check_task_metrics_type(task_metrics)
        super().__init__()
        self.task_metrics = task_metrics

    @staticmethod
    def _check_task_metrics_type(task_metrics: Dict[str, Union[Metric, MetricCollection]]) -> None:
        if not isinstance(task_metrics, dict):
            raise TypeError(f"Expected argument `task_metrics` to be a dict. Found task_metrics = {task_metrics}")
        for metric in task_metrics.values():
            if not (isinstance(metric, (Metric, MetricCollection))):
                raise TypeError(
                    "Expected each task's metric to be a Metric or a MetricCollection. "
                    f"Found a metric of type {type(metric)}"
                )

    def update(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> None:
        """Update each task's metric with its (preds, target) pair."""
        if not self.task_metrics.keys() == task_preds.keys() == task_targets.keys():
            raise ValueError(
                "Expected arguments `task_preds` and `task_targets` to have the same keys as the wrapped `task_metrics`."
                f" Found task_preds.keys() = {task_preds.keys()}, task_targets.keys() = {task_targets.keys()} "
                f"and self.task_metrics.keys() = {self.task_metrics.keys()}"
            )
        for task_name, metric in self.task_metrics.items():
            metric.update(task_preds[task_name], task_targets[task_name])

    def compute(self) -> Dict[str, Any]:
        """Per-task results."""
        return {task_name: metric.compute() for task_name, metric in self.task_metrics.items()}

    def forward(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> Dict[str, Any]:
        """Per-task batch values."""
        return {
            task_name: metric(task_preds[task_name], task_targets[task_name])
            for task_name, metric in self.task_metrics.items()
        }

    def reset(self) -> None:
        """Reset all task metrics."""
        for metric in self.task_metrics.values():
            metric.reset()
        super().reset()

    def _wrap_update(self, update: Any) -> Any:
        return update

    def _wrap_compute(self, compute: Any) -> Any:
        return compute
