"""Bootstrapped confidence intervals for any metric.

Capability parity: reference ``wrappers/bootstrapping.py:30-52`` (sampler ``:30``).
Resampling indices are drawn host-side (numpy) per update — same as the reference's
eager ``torch.distributions`` draw — then the gather runs on device.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import apply_to_collection

Array = jax.Array


def _bootstrap_sampler(
    size: int,
    sampling_strategy: str = "poisson",
    rng: Optional[np.random.RandomState] = None,
) -> Array:
    """Resample indices with replacement (reference ``bootstrapping.py:30-50``)."""
    rng = rng or np.random
    if sampling_strategy == "poisson":
        n = rng.poisson(1, size)
        return jnp.asarray(np.repeat(np.arange(size), n))
    if sampling_strategy == "multinomial":
        return jnp.asarray(rng.randint(0, size, size))
    raise ValueError("Unknown sampling strategy")


class BootStrapper(Metric):
    """Keep ``num_bootstraps`` copies of a metric, each updated on a resampled batch (reference ``bootstrapping.py:52``).

    Example:
        >>> import numpy as np
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import BootStrapper, MeanMetric
        >>> boot = BootStrapper(MeanMetric(), num_bootstraps=4)
        >>> boot._rng = np.random.RandomState(0)  # seeded for a reproducible example
        >>> boot.update(jnp.asarray([1.0, 2.0, 3.0, 4.0]))
        >>> out = boot.compute()
        >>> sorted(out.keys())
        ['mean', 'std']
        >>> bool(out['std'] >= 0)
        True
    """

    full_state_update: Optional[bool] = True

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of torchmetrics_tpu.Metric but received {base_metric}"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps
        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw
        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy
        self._rng = np.random.RandomState()

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample inputs along dim 0 per bootstrap copy, then update each copy."""
        args_sizes = apply_to_collection(args, (jnp.ndarray, jax.Array), lambda x: x.shape[0])
        kwargs_sizes = apply_to_collection(kwargs, (jnp.ndarray, jax.Array), lambda x: x.shape[0])
        if len(args_sizes) > 0:
            size = args_sizes[0]
        elif len(kwargs_sizes) > 0:
            size = next(iter(kwargs_sizes.values()))
        else:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            if sample_idx.size == 0:
                continue
            new_args = apply_to_collection(args, (jnp.ndarray, jax.Array), jnp.take, sample_idx, axis=0)
            new_kwargs = apply_to_collection(kwargs, (jnp.ndarray, jax.Array), jnp.take, sample_idx, axis=0)
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """mean/std/quantile/raw over bootstrap values (reference ``bootstrapping.py``)."""
        computed_vals = jnp.stack([m.compute() for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = computed_vals.mean(axis=0)
        if self.std:
            output_dict["std"] = computed_vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile, axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def reset(self) -> None:
        """Reset all bootstrap copies."""
        for m in self.metrics:
            m.reset()
        super().reset()

    def plot(self, val: Optional[Union[Array, Sequence[Array]]] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
