"""Multioutput wrapper (reference ``wrappers/multioutput.py:29``)."""

from __future__ import annotations

from copy import deepcopy
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import apply_to_collection

Array = jax.Array


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows where any tensor has a NaN (reference ``multioutput.py:16-26``)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = tensors[0]
    nan_idxs = jnp.zeros(len(sentinel), dtype=bool)
    for tensor in tensors:
        permuted_tensor = tensor.reshape(len(sentinel), -1)
        nan_idxs = nan_idxs | jnp.any(jnp.isnan(permuted_tensor), axis=1)
    return nan_idxs


class MultioutputWrapper(Metric):
    """One metric clone per output column (reference ``multioutput.py:29``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanSquaredError, MultioutputWrapper
        >>> metric = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> preds = jnp.asarray([[1.0, 10.0], [2.0, 20.0]])
        >>> target = jnp.asarray([[1.0, 12.0], [2.0, 22.0]])
        >>> metric.update(preds, target)
        >>> [round(float(v), 2) for v in metric.compute()]
        [0.0, 4.0]
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
    ) -> None:
        super().__init__()
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple]:
        """Slice inputs per output (reference ``multioutput.py:93-113``)."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            selected_args = apply_to_collection(
                args, (jnp.ndarray, jax.Array), jnp.take, jnp.asarray([i]), axis=self.output_dim
            )
            selected_kwargs = apply_to_collection(
                kwargs, (jnp.ndarray, jax.Array), jnp.take, jnp.asarray([i]), axis=self.output_dim
            )
            if self.remove_nans:
                args_kwargs = selected_args + tuple(selected_kwargs.values())
                nan_idxs = np.asarray(_get_nan_indices(*args_kwargs))
                selected_args = [jnp.asarray(np.asarray(arg)[~nan_idxs]) for arg in selected_args]
                selected_kwargs = {k: jnp.asarray(np.asarray(v)[~nan_idxs]) for k, v in selected_kwargs.items()}
            if self.squeeze_outputs:
                selected_args = [arg.squeeze(self.output_dim) for arg in selected_args]
                selected_kwargs = {k: v.squeeze(self.output_dim) for k, v in selected_kwargs.items()}
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update each underlying metric with its output slice."""
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        """Stacked per-output values."""
        return jnp.stack([m.compute() for m in self.metrics], 0)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Per-output batch values."""
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        results = [
            metric(*selected_args, **selected_kwargs)
            for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs)
        ]
        if results[0] is None:
            return None
        return jnp.stack(results, 0)

    def reset(self) -> None:
        """Reset all underlying metrics."""
        for metric in self.metrics:
            metric.reset()
        super().reset()

    def plot(self, val: Optional[Union[Array, Sequence[Array]]] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
