"""MetricTracker — historical per-step clones (reference ``wrappers/tracker.py:31``)."""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


class MetricTracker:
    """Track a metric (or collection) across steps/epochs (reference ``tracker.py:31``).

    ``increment()`` snapshots a fresh clone; ``best_metric()`` scans history.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MetricTracker, MeanMetric
        >>> tracker = MetricTracker(MeanMetric())
        >>> for epoch_vals in ([1.0, 2.0], [3.0, 4.0]):
        ...     tracker.increment()
        ...     for v in epoch_vals:
        ...         tracker.update(jnp.asarray(v))
        >>> print([float(v) for v in tracker.compute_all()])
        [1.5, 3.5]
        >>> best, which = tracker.best_metric(return_step=True)
        >>> print(float(best), which)
        3.5 1
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a torchmetrics_tpu"
                f" `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
            raise ValueError("The len of argument `maximize` should match the length of the metric collection")
        if isinstance(metric, Metric) and not isinstance(maximize, bool):
            raise ValueError("Argument `maximize` should be a single bool when `metric` is a single Metric")
        self.maximize = maximize
        self._metrics: List[Union[Metric, MetricCollection]] = []
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Number of tracked steps."""
        return len(self._metrics)

    def __len__(self) -> int:
        return len(self._metrics)

    def __getitem__(self, idx: int) -> Union[Metric, MetricCollection]:
        return self._metrics[idx]

    def increment(self) -> None:
        """Start tracking a new step with a fresh clone (reference ``tracker.py:130-133``)."""
        self._increment_called = True
        self._metrics.append(deepcopy(self._base_metric))

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Forward on the current step's metric."""
        self._check_for_increment("forward")
        return self._metrics[-1](*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the current step's metric."""
        self._check_for_increment("update")
        self._metrics[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        """Compute the current step's metric."""
        self._check_for_increment("compute")
        return self._metrics[-1].compute()

    def compute_all(self) -> Any:
        """Stacked values across all steps (reference ``tracker.py:150-168``)."""
        self._check_for_increment("compute_all")
        res = [metric.compute() for metric in self._metrics]
        try:
            if isinstance(res[0], dict):
                keys = res[0].keys()
                return {k: jnp.stack([jnp.asarray(r[k]) for r in res], axis=0) for k in keys}
            if isinstance(res[0], list):
                return jnp.stack([jnp.stack([jnp.asarray(r2) for r2 in r], axis=0) for r in res], 0)
            return jnp.stack([jnp.asarray(r) for r in res], axis=0)
        except TypeError:
            return res

    def reset(self) -> None:
        """Reset the current step's metric."""
        self._metrics[-1].reset()

    def reset_all(self) -> None:
        """Reset every tracked metric."""
        for metric in self._metrics:
            metric.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[
        None, float, Tuple[float, int], Tuple[None, None],
        Dict[str, Optional[float]], Tuple[Dict[str, Optional[float]], Dict[str, Optional[int]]],
    ]:
        """Best value (and optionally step) across history (reference ``tracker.py:184-260``)."""
        res = self.compute_all()
        if isinstance(res, dict):
            maximize = self.maximize if isinstance(self.maximize, list) else len(res) * [self.maximize]
            value, idx = {}, {}
            for i, (k, v) in enumerate(res.items()):
                try:
                    arr = np.asarray(v)
                    best = arr.argmax(0) if maximize[i] else arr.argmin(0)
                    value[k] = float(arr[int(best)])
                    idx[k] = int(best)
                except (ValueError, TypeError) as error:
                    rank_zero_warn(
                        f"Encountered the following error when trying to get the best metric for metric {k}:"
                        f"{error}. Returning `None` instead.",
                        UserWarning,
                    )
                    value[k] = None
                    idx[k] = None
            return (value, idx) if return_step else value
        try:
            arr = np.asarray(res)
            best = int(arr.argmax(0) if self.maximize else arr.argmin(0))
            return (float(arr[best]), best) if return_step else float(arr[best])
        except (ValueError, TypeError) as error:
            rank_zero_warn(
                f"Encountered the following error when trying to get the best metric: {error}."
                " Returning `None` instead.",
                UserWarning,
            )
            return (None, None) if return_step else None

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called.")

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        """Plot the tracked values over steps (reference ``tracker.py:270``)."""
        from torchmetrics_tpu.utilities.plot import plot_single_or_multi_val

        val = val if val is not None else [self._metrics[i].compute() for i in range(self.n_steps)]
        return plot_single_or_multi_val(val, ax=ax, name=self._base_metric.__class__.__name__)
