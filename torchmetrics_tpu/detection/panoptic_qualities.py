"""Modular PanopticQuality / ModifiedPanopticQuality (reference ``detection/panoptic_qualities.py``).

Dense per-category sum states ride the ordinary psum sync path — PQ is the one
detection metric whose state is mesh-friendly by construction.
"""

from __future__ import annotations

from typing import Any, Collection, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.detection._panoptic_common import (
    _get_category_id_to_continuous_id,
    _get_void_color,
    _panoptic_quality_compute,
    _panoptic_quality_update,
    _parse_categories,
    _preprocess_inputs,
    _validate_inputs,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class PanopticQuality(Metric):
    """Panoptic Quality with per-category sum states (reference ``panoptic_qualities.py:27-215``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[[0, 0], [0, 1], [6, 0], [7, 0], [0, 2]]])
        >>> target = jnp.asarray([[[0, 1], [0, 1], [6, 0], [7, 0], [1, 0]]])
        >>> from torchmetrics_tpu.detection.panoptic_qualities import PanopticQuality
        >>> metric = PanopticQuality(things={0, 1}, stuffs={6, 7})
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.5
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    iou_sum: Array
    true_positives: Array
    false_positives: Array
    false_negatives: Array

    _modified_variant: bool = False

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        things, stuffs = _parse_categories(things, stuffs)
        self.things = things
        self.stuffs = stuffs
        self.void_color = _get_void_color(things, stuffs)
        self.cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
        self.allow_unknown_preds_category = allow_unknown_preds_category

        n_categories = len(things) + len(stuffs)
        self.add_state("iou_sum", default=jnp.zeros(n_categories), dist_reduce_fx="sum")
        self.add_state("true_positives", default=jnp.zeros(n_categories, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("false_positives", default=jnp.zeros(n_categories, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("false_negatives", default=jnp.zeros(n_categories, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Fold one batch of (category, instance) maps into the category stats."""
        _validate_inputs(preds, target)
        flatten_preds = _preprocess_inputs(
            self.things, self.stuffs, preds, self.void_color, self.allow_unknown_preds_category
        )
        flatten_target = _preprocess_inputs(self.things, self.stuffs, target, self.void_color, True)
        iou_sum, tp, fp, fn = _panoptic_quality_update(
            flatten_preds,
            flatten_target,
            self.cat_id_to_continuous_id,
            self.void_color,
            modified_metric_stuffs=self.stuffs if self._modified_variant else None,
        )
        self.iou_sum = self.iou_sum + iou_sum
        self.true_positives = self.true_positives + tp.astype(self.true_positives.dtype)
        self.false_positives = self.false_positives + fp.astype(self.false_positives.dtype)
        self.false_negatives = self.false_negatives + fn.astype(self.false_negatives.dtype)

    def compute(self) -> Array:
        """Category-averaged PQ."""
        return _panoptic_quality_compute(
            self.iou_sum, self.true_positives, self.false_positives, self.false_negatives
        )

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class ModifiedPanopticQuality(PanopticQuality):
    """PQ variant with per-segment stuff scoring (reference ``panoptic_qualities.py:218-355``)."""

    _modified_variant: bool = True
