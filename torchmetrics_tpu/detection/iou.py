"""Modular IntersectionOverUnion (reference ``detection/iou.py:38-230``).

The GIoU/DIoU/CIoU modular metrics subclass this one, swapping the pairwise kernel —
the reference repeats the class four times instead (``detection/{giou,diou,ciou}.py``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.detection.helpers import _fix_empty_tensors, _input_validator
from torchmetrics_tpu.functional.detection._iou_variants import _variant_compute, _variant_update
from torchmetrics_tpu.functional.detection.helpers import _box_convert, _box_iou
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class IntersectionOverUnion(Metric):
    """Mean IoU over matched detection/ground-truth boxes (reference ``iou.py:38``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.detection import IntersectionOverUnion
        >>> preds = [{'boxes': jnp.asarray([[296.55, 93.96, 314.97, 152.79]]), 'scores': jnp.asarray([0.236]), 'labels': jnp.asarray([4])}]
        >>> target = [{'boxes': jnp.asarray([[300.00, 100.00, 315.00, 150.00]]), 'labels': jnp.asarray([4])}]
        >>> metric = IntersectionOverUnion()
        >>> print({k: round(float(v), 4) for k, v in metric(preds, target).items()})
        {'iou': 0.6898}
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = True

    detection_labels: List[Array]
    groundtruth_labels: List[Array]
    results: List[Array]

    _iou_type: str = "iou"
    _invalid_val: float = 0.0
    _iou_kernel: Callable[[Array, Array], Array] = staticmethod(_box_iou)

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_threshold: Optional[float] = None,
        class_metrics: bool = False,
        respect_labels: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_threshold = iou_threshold
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if not isinstance(respect_labels, bool):
            raise ValueError("Expected argument `respect_labels` to be a boolean")
        self.respect_labels = respect_labels

        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)
        self.add_state("results", default=[], dist_reduce_fx=None)

    def update(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        """Score one batch of per-image box dicts (reference ``iou.py:167-212``)."""
        _input_validator(preds, target)

        for p, t in zip(preds, target):
            det_boxes = self._get_safe_item_values(p["boxes"])
            gt_boxes = self._get_safe_item_values(t["boxes"])
            p_labels = jnp.asarray(p["labels"])
            t_labels = jnp.asarray(t["labels"])
            self.detection_labels.append(p_labels)
            self.groundtruth_labels.append(t_labels)

            ious = _variant_update(
                type(self)._iou_kernel, det_boxes, gt_boxes, self.iou_threshold, self._invalid_val
            )
            if self.respect_labels and ious.size > 0:
                # applied unconditionally on-device: when labels agree the mask is all
                # False and this is the identity — no host sync in the hot loop
                labels_not_eq = p_labels[:, None] != t_labels[None, :]
                ious = jnp.where(labels_not_eq, self._invalid_val, ious)
            self.results.append(ious.astype(jnp.float32))

    def _get_safe_item_values(self, boxes: Array) -> Array:
        boxes = _fix_empty_tensors(jnp.asarray(boxes, dtype=jnp.float32))
        if boxes.size > 0:
            boxes = _box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
        return boxes

    def _get_gt_classes(self) -> List[int]:
        if len(self.groundtruth_labels) > 0:
            return np.unique(np.concatenate([np.asarray(x).reshape(-1) for x in self.groundtruth_labels])).astype(
                int
            ).tolist()
        return []

    def compute(self) -> Dict[str, Array]:
        """Aggregate the per-image score matrices (reference ``iou.py:226-248``)."""
        per_image = []
        for iou_mat, d_labels, g_labels in zip(self.results, self.detection_labels, self.groundtruth_labels):
            if iou_mat.size == 0:
                continue  # object-free image: nothing to average, don't poison with NaN
            d_np = np.asarray(d_labels).reshape(-1)
            g_np = np.asarray(g_labels).reshape(-1)
            labels_eq = d_np.shape == g_np.shape and bool((d_np == g_np).all())
            per_image.append(jnp.atleast_1d(_variant_compute(iou_mat, labels_eq)))
        aggregated = dim_zero_cat(per_image) if per_image else jnp.zeros((0,))
        results: Dict[str, Array] = {self._iou_type: aggregated.mean() if aggregated.size else jnp.asarray(0.0)}

        if self.class_metrics:
            gt_classes = self._get_gt_classes()
            for cl in gt_classes:
                masked_scores, observed = [], 0
                for iou_mat, d_labels, g_labels in zip(self.results, self.detection_labels, self.groundtruth_labels):
                    if iou_mat.size == 0:
                        continue
                    sel = (np.asarray(d_labels).reshape(-1, 1) == cl) & (np.asarray(g_labels).reshape(1, -1) == cl)
                    if sel.any():
                        masked_scores.append(jnp.asarray(np.asarray(iou_mat)[sel]).reshape(-1))
                        observed += 1
                if masked_scores:
                    results[f"{self._iou_type}/cl_{cl}"] = dim_zero_cat(masked_scores).mean()
        return results

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
