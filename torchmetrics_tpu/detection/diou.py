"""Modular DistanceIntersectionOverUnion (reference ``detection/diou.py``)."""

from __future__ import annotations

from typing import Callable

from torchmetrics_tpu.detection.iou import IntersectionOverUnion
from torchmetrics_tpu.functional.detection.helpers import _box_diou


class DistanceIntersectionOverUnion(IntersectionOverUnion):
    """Mean DIoU over matched boxes; DIoU ranges in [-1, 1] so invalid pairs get -1."""

    _iou_type: str = "diou"
    _invalid_val: float = -1.0
    _iou_kernel: Callable = staticmethod(_box_diou)
