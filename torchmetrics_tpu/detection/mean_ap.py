"""MeanAveragePrecision (reference ``detection/mean_ap.py:150-970``).

Architecture: the metric streams raw per-image arrays into five ``dist_reduce_fx=None``
list states (reference ``mean_ap.py:358-362``), exactly the shape the kernel's raw-state
sync path handles. ``compute()`` is an epoch-end evaluation with COCOeval semantics:

- box IoU matrices come from the vectorized jnp kernel in
  ``functional/detection/helpers.py`` (one broadcasted pass per image/class);
- for ``iou_type="segm"`` masks are dense booleans and the IoU reduces to a
  flatten-and-matmul — MXU-friendly, unlike the reference's pycocotools RLE C path
  (``mean_ap.py:38,131``);
- the greedy best-GT matching and PR accumulation run on host numpy: they are
  data-dependent ragged loops over tens of detections, which the reference also keeps
  off-accelerator (``_move_list_states_to_cpu``, ``mean_ap.py:380``). States are
  fetched from device exactly once, at the top of ``compute``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.detection.helpers import _fix_empty_tensors, _input_validator
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.engine.stats import EngineStats
from torchmetrics_tpu.functional.detection.helpers import _box_convert, _box_iou
from torchmetrics_tpu.metric import Metric

Array = jax.Array

# module-level stats block: the retained host evaluator is a heavy-workload
# fallback fact (the packed-array route has an in-graph sibling in
# ``detection/ingraph.py``) — one EngineStats joins the weak registry so
# engine_report()/telemetry aggregate `map_host_evals` like any other counter
_STATS = EngineStats("mean_ap")

_LABEL_F32_BOUND_MSG = (
    "Packed `{}` labels reach |{}| >= 2**24: class ids of that magnitude are not"
    " exactly representable in the f32 packed channel and would be silently rounded"
    " to a wrong class. Use the per-image list update path for such ids."
)


def _check_packed_label_bound(name: str, labels_2d: np.ndarray, counts: np.ndarray) -> None:
    """Raise when any VALID-row label magnitude breaks f32 exactness (|v| >= 2**24).

    Rows past each image's count are padding and may hold sentinels; they are
    never read back, so they are exempt.
    """
    valid = np.arange(labels_2d.shape[-1]) < np.asarray(counts).reshape(-1, 1)
    masked = np.abs(np.where(valid, labels_2d, 0))
    if masked.size and float(masked.max()) >= 2**24:
        raise ValueError(_LABEL_F32_BOUND_MSG.format(name, int(masked.max())))


def _validate_packed_batch(pp: np.ndarray, pc: np.ndarray, tt: np.ndarray, tc: np.ndarray) -> None:
    """Shared packed-batch invariants for both compute paths (native + fallback).

    Count-range check FIRST: an out-of-range count would make the label bound
    check misread sentinel padding as real labels. The f32-exactness bound runs
    on the already-fetched host buffers (any original id with |v| >= 2**24 lands
    here with |packed| >= 2**24, so detection after the cast is sound; device
    arrays at update time could not be checked without an extra fetch).
    """
    if (pc < 0).any() or (pc > pp.shape[1]).any() or (tc < 0).any() or (tc > tt.shape[1]).any():
        raise ValueError(
            f"Packed num_boxes out of range: counts must lie in [0, padded width]"
            f" ({pp.shape[1]} preds / {tt.shape[1]} target) — a count past the padding"
            " would silently drop boxes"
        )
    _check_packed_label_bound("preds", pp[..., 5], pc)
    _check_packed_label_bound("target", tt[..., 4], tc)


def _f64(arr: np.ndarray) -> np.ndarray:
    """float64 ingestion matching the C++ evaluator (``coco_eval_bbox`` takes
    f64 boxes), so a threshold-straddling IoU cannot flip between the native
    path and the Python fallback on float32 rounding alone. No copy when the
    input is already f64 — shared by both IoU kernels and the area helper."""
    return arr.astype(np.float64, copy=False)


def _safe_iou(inter: np.ndarray, union: np.ndarray) -> np.ndarray:
    """The shared zero-union guard: pairs with an empty union define IoU as 0
    (degenerate zero-area boxes / empty masks must not divide by zero)."""
    return inter / np.where(union == 0, 1.0, union)


def _np_box_iou(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """Host-side pairwise IoU used inside the ragged evaluation loops."""
    if det.size == 0 or gt.size == 0:
        return np.zeros((det.shape[0], gt.shape[0]))
    det = _f64(det)
    gt = _f64(gt)
    area1 = (det[:, 2] - det[:, 0]) * (det[:, 3] - det[:, 1])
    area2 = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1])
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return _safe_iou(inter, union)


def _np_mask_iou(det, gt) -> np.ndarray:
    """Pairwise mask IoU: dense masks via one flattened matmul, RLEs via the native kernel."""
    if _is_rle_list(det) or _is_rle_list(gt):
        from torchmetrics_tpu.native import rle_encode, rle_iou

        # mixed inputs: encode the dense side so one O(runs) kernel handles the pair
        det_rle = list(det) if _is_rle_list(det) else [rle_encode(m) for m in np.asarray(det)]
        gt_rle = list(gt) if _is_rle_list(gt) else [rle_encode(m) for m in np.asarray(gt)]
        return rle_iou(det_rle, gt_rle)
    if det.size == 0 or gt.size == 0:
        return np.zeros((det.shape[0], gt.shape[0]))
    d = _f64(det.reshape(det.shape[0], -1))
    g = _f64(gt.reshape(gt.shape[0], -1))
    inter = d @ g.T
    union = d.sum(axis=1)[:, None] + g.sum(axis=1)[None, :] - inter
    return _safe_iou(inter, union)


def _bulk_to_host(items: List[Any]) -> List[Any]:
    """Fetch a whole list state in one batched device->host transfer.

    Per-element ``np.asarray`` issues one synchronous round-trip each — on a tunneled
    TPU that is ~100 ms per fetch, turning a 500-image epoch-end ``compute()`` into
    minutes. ``jax.device_get`` batches the copies for the entire list in a single
    call (and involves no device computation, so nothing to compile). Host-side
    entries (RLE dicts, already-numpy arrays) pass through.

    The fetch rides the sanctioned ``map-host-matcher`` transfer boundary: the
    retained host evaluator is a DECLARED epoch-end readback, so a strict
    transfer guard around an eval loop stays clean by declaration rather than
    suppression.
    """
    if not items:
        return []
    from torchmetrics_tpu.diag.transfer_guard import transfer_allowed

    with transfer_allowed("map-host-matcher"):
        device_idx = [i for i, x in enumerate(items) if isinstance(x, jax.Array)]
        fetched = jax.device_get([items[i] for i in device_idx])
        # device entries are ONLY filled from the batched fetch (converting them in the
        # comprehension would fall back to one synchronous round-trip each)
        out = [x if _is_rle_list(x) or isinstance(x, jax.Array) else np.asarray(x) for x in items]
        for i, val in zip(device_idx, fetched):
            out[i] = np.asarray(val)
    return out


def _is_rle_list(values) -> bool:
    """True for a sequence of COCO-style ``{"size", "counts"}`` RLE dicts."""
    return isinstance(values, (list, tuple)) and (len(values) == 0 or isinstance(values[0], dict))


def _take(values, selector):
    """Row-select that works for both ndarray stacks and RLE lists."""
    if _is_rle_list(values):
        idx = np.flatnonzero(selector) if np.asarray(selector).dtype == bool else np.asarray(selector)
        return [values[i] for i in idx]
    return values[selector]


def _n_items(values) -> int:
    return len(values) if _is_rle_list(values) else values.shape[0]


def _area(values, iou_type: str) -> np.ndarray:
    """Box or mask areas for the ignore-range logic."""
    if _is_rle_list(values):
        from torchmetrics_tpu.native import rle_area

        return np.asarray([rle_area(r) for r in values], dtype=np.float64)
    if values.size == 0:
        return np.zeros((values.shape[0],))
    if iou_type == "bbox":
        # f64 ingestion mirrors the C++ evaluator's area computation, keeping the
        # area-range ignore decisions identical between the two paths
        values = _f64(values)
        return (values[:, 2] - values[:, 0]) * (values[:, 3] - values[:, 1])
    return values.reshape(values.shape[0], -1).sum(axis=1)


class MeanAveragePrecision(Metric):
    """mAP/mAR for object detection with COCOeval semantics (reference ``mean_ap.py:150``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = [{'boxes': jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), 'scores': jnp.asarray([0.9]), 'labels': jnp.asarray([0])}]
        >>> target = [{'boxes': jnp.asarray([[12.0, 10.0, 58.0, 62.0]]), 'labels': jnp.asarray([0])}]
        >>> from torchmetrics_tpu.detection.mean_ap import MeanAveragePrecision
        >>> metric = MeanAveragePrecision()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(round(float(metric.compute()['map']), 4)), 4))
        0.8
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    detections: List[Array]
    detection_scores: List[Array]
    detection_labels: List[Array]
    groundtruths: List[Array]
    groundtruth_labels: List[Array]

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        allowed_iou_types = ("segm", "bbox")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, round((0.95 - 0.5) / 0.05) + 1).tolist()
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.00, round(1.00 / 0.01) + 1).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        if iou_type not in allowed_iou_types:
            raise ValueError(f"Expected argument `iou_type` to be one of {allowed_iou_types} but got {iou_type}")
        self.iou_type = iou_type
        self.bbox_area_ranges = {
            "all": (float(0**2), float(1e5**2)),
            "small": (float(0**2), float(32**2)),
            "medium": (float(32**2), float(96**2)),
            "large": (float(96**2), float(1e5**2)),
        }

        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics

        self.add_state("detections", default=[], dist_reduce_fx=None)
        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruths", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)
        # TPU-first packed fast path (see update): one buffer per update call
        self.add_state("packed_preds", default=[], dist_reduce_fx=None)
        self.add_state("packed_pred_counts", default=[], dist_reduce_fx=None)
        self.add_state("packed_targets", default=[], dist_reduce_fx=None)
        self.add_state("packed_target_counts", default=[], dist_reduce_fx=None)

    def update(self, preds: Any, target: Any) -> None:
        """Buffer one batch of predictions/targets.

        Two input forms:

        - Reference parity (``mean_ap.py:364-378``): sequences of per-image dicts
          (``boxes``/``scores``/``labels``). Each image contributes 5 device
          buffers, each a separate device->host copy at ``compute`` — ~0.6 ms per
          buffer through a tunneled TPU, which dominates COCO-scale epochs.
        - TPU-first packed batches: ``preds = {"boxes": (B, M, 4), "scores":
          (B, M), "labels": (B, M), "num_boxes": (B,)}`` and ``target`` likewise
          without scores — the padded layout a batched NMS produces on device.
          One buffer per update call regardless of batch size, so a 5k-image
          epoch fetches ~tens of buffers instead of ~50k (bbox iou_type only).
        """
        if isinstance(preds, dict) and isinstance(target, dict):
            self._update_packed(preds, target)
            return
        _input_validator(preds, target, iou_type=self.iou_type)

        for item in preds:
            self.detections.append(self._get_safe_item_values(item))
            self.detection_labels.append(jnp.asarray(item["labels"]))
            self.detection_scores.append(jnp.asarray(item["scores"]))

        for item in target:
            self.groundtruths.append(self._get_safe_item_values(item))
            self.groundtruth_labels.append(jnp.asarray(item["labels"]))

    def _update_packed(self, preds: Dict[str, Array], target: Dict[str, Array]) -> None:
        """Fold a padded batch into single-buffer states.

        Boxes are converted to xyxy and packed with scores/labels into one
        ``(B, M, 6)`` float32 array (labels are exact in f32 below 2**24); valid
        counts ride as ``(B,)`` int arrays. Padding rows are never read back:
        ``compute`` slices each image to its count.
        """
        if self.iou_type != "bbox":
            raise ValueError("Packed batch updates support iou_type='bbox' only")
        for name, d, keys in (("preds", preds, ("boxes", "scores", "labels", "num_boxes")),
                              ("target", target, ("boxes", "labels", "num_boxes"))):
            missing = [k for k in keys if k not in d]
            if missing:
                raise ValueError(f"Packed `{name}` dict is missing keys {missing}")
        p_boxes = jnp.asarray(preds["boxes"], dtype=jnp.float32)
        t_boxes = jnp.asarray(target["boxes"], dtype=jnp.float32)
        if p_boxes.ndim != 3 or p_boxes.shape[-1] != 4 or t_boxes.ndim != 3 or t_boxes.shape[-1] != 4:
            raise ValueError(
                f"Packed boxes must be (B, M, 4), got {p_boxes.shape} and {t_boxes.shape}"
            )
        if p_boxes.shape[0] != t_boxes.shape[0]:
            raise ValueError("Packed preds and target must share the batch dimension")
        b, m = p_boxes.shape[:2]
        for name, lbl, cnt in (
            ("preds", preds["labels"], preds["num_boxes"]),
            ("target", target["labels"], target["num_boxes"]),
        ):
            # Validate the f32-exactness bound WITHOUT a device fetch: host inputs
            # (numpy/lists) are checked here for an early, per-call error; device
            # arrays are checked once at compute on the already-fetched buffers
            # (see _unpack_into), preserving the single-fetch-at-compute invariant.
            if isinstance(lbl, (np.ndarray, list, tuple)) and isinstance(cnt, (np.ndarray, list, tuple, int)):
                lbl_np = np.asarray(lbl)
                if lbl_np.ndim >= 2:  # malformed shapes fall through to pack-time validation
                    _check_packed_label_bound(name, lbl_np, np.asarray(cnt))
        if self.box_format != "xyxy":
            p_boxes = _box_convert(p_boxes.reshape(-1, 4), in_fmt=self.box_format, out_fmt="xyxy").reshape(b, m, 4)
            t_boxes = _box_convert(t_boxes.reshape(-1, 4), in_fmt=self.box_format, out_fmt="xyxy").reshape(*t_boxes.shape)
        packed_p = jnp.concatenate(
            [
                p_boxes,
                jnp.asarray(preds["scores"], jnp.float32)[..., None],
                jnp.asarray(preds["labels"], jnp.float32)[..., None],
            ],
            axis=-1,
        )
        packed_t = jnp.concatenate(
            [t_boxes, jnp.asarray(target["labels"], jnp.float32)[..., None]], axis=-1
        )
        self.packed_preds.append(packed_p)
        self.packed_pred_counts.append(jnp.asarray(preds["num_boxes"], jnp.int32))
        self.packed_targets.append(packed_t)
        self.packed_target_counts.append(jnp.asarray(target["num_boxes"], jnp.int32))

    def _get_safe_item_values(self, item: Dict[str, Any]) -> Any:
        if self.iou_type == "bbox":
            boxes = _fix_empty_tensors(jnp.asarray(item["boxes"], dtype=jnp.float32))
            if boxes.size > 0:
                boxes = _box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
            return boxes
        masks = item["masks"]
        if _is_rle_list(masks):
            # COCO-style uncompressed RLE dicts: kept on host, evaluated by the
            # native C++ kernel (torchmetrics_tpu/native/rle.cpp)
            return list(masks)
        # dense boolean masks (num_boxes, H, W)
        return jnp.asarray(masks, dtype=bool)

    def _unpack_into(
        self,
        dets: List[np.ndarray],
        det_scores: List[np.ndarray],
        det_labels: List[np.ndarray],
        gts: List[np.ndarray],
        gt_labels: List[np.ndarray],
    ) -> None:
        """Expand packed batch states into the per-image host lists.

        A handful of large buffers comes down in one batched fetch; the per-image
        splitting is host-side numpy slicing (free next to tunnel round-trips).
        """
        if not self.packed_preds:
            return
        packed_p = _bulk_to_host(self.packed_preds)
        p_counts = _bulk_to_host(self.packed_pred_counts)
        packed_t = _bulk_to_host(self.packed_targets)
        t_counts = _bulk_to_host(self.packed_target_counts)
        for pp, pc, tt, tc in zip(packed_p, p_counts, packed_t, t_counts):
            _validate_packed_batch(pp, pc, tt, tc)
            for i in range(pp.shape[0]):
                n = int(pc[i])
                dets.append(pp[i, :n, :4].astype(np.float32))
                det_scores.append(pp[i, :n, 4])
                det_labels.append(pp[i, :n, 5].astype(np.int64))
                ng = int(tc[i])
                gts.append(tt[i, :ng, :4].astype(np.float32))
                gt_labels.append(tt[i, :ng, 4].astype(np.int64))

    @staticmethod
    def _get_classes(det_labels: List[np.ndarray], gt_labels: List[np.ndarray]) -> List[int]:
        """Unique classes present in either stream (reference ``mean_ap.py:406-410``)."""
        if len(det_labels) > 0 or len(gt_labels) > 0:
            return np.unique(np.concatenate(det_labels + gt_labels)).astype(int).tolist()
        return []

    # ---------------------------------------------------------------- compute

    def compute(self) -> Dict[str, Array]:
        """COCOeval over the buffered epoch (reference ``mean_ap.py:846-875``).

        This IS the retained host evaluator (list/RLE route + packed fallback):
        every compute is counted as a heavy-workload host fallback
        (``map_host_evals`` / ``heavy.fallback``) so operators can see from a
        scrape which eval loops still pay host matching — the in-graph
        packed-route sibling is
        :class:`~torchmetrics_tpu.detection.ingraph.PackedMeanAveragePrecision`.
        """
        if jax.core.trace_state_clean():
            # the epoch engine's (always-aborted) trace attempt enters this
            # body once before demoting to eager — only the eager evaluation
            # that actually runs the host matcher counts
            _STATS.map_host_evals += 1
            _diag.record(
                "heavy.fallback", type(self).__name__,
                label="map-host-matcher", reason="host-route",
            )
        if self.iou_type == "bbox":
            from torchmetrics_tpu.native import coco_eval_bbox_available

            # the native evaluator's PR-interpolation cursor assumes ascending
            # rec_thresholds (the COCO default); anything else rides the
            # per-threshold-searchsorted Python path so both paths stay exact
            rec = np.asarray(self.rec_thresholds)
            if coco_eval_bbox_available() and bool(np.all(np.diff(rec) >= 0)):
                return self._compute_native_bbox()

        # ONE batched D2H fetch per list state (RLE lists are already host data)
        dets = _bulk_to_host(self.detections)
        det_scores = _bulk_to_host(self.detection_scores)
        det_labels = [l.reshape(-1) for l in _bulk_to_host(self.detection_labels)]
        gts = _bulk_to_host(self.groundtruths)
        gt_labels = [l.reshape(-1) for l in _bulk_to_host(self.groundtruth_labels)]
        self._unpack_into(dets, det_scores, det_labels, gts, gt_labels)

        classes = self._get_classes(det_labels, gt_labels)
        precisions, recalls = self._calculate(classes, dets, det_scores, det_labels, gts, gt_labels)
        return self._finalize(precisions, recalls, classes)

    def _compute_native_bbox(self) -> Dict[str, Array]:
        """Epoch-end compute on the C++ fast path: flat epoch arrays, one call.

        Replaces the per-image Python unpack + per-(class, image) evaluation loop
        with vectorized numpy flattening (packed states extract by mask, no
        per-image slicing) and a single ``coco_eval_bbox`` call that does
        bucketing, per-image score sort, IoU, greedy matching, and PR-curve
        accumulation natively. The Python fallback ingests boxes as float64
        exactly like this path does (``_np_box_iou``/``_area``), so the two
        agree bit-for-bit on f32-representable inputs (pinned by
        ``tests/detection/test_native_eval_parity.py``); score TIE ordering at
        identical float scores remains sort-implementation-defined in both.
        """
        from torchmetrics_tpu.native import coco_eval_bbox

        det_parts, score_parts, dlab_parts, dimg_parts = [], [], [], []
        gt_parts, glab_parts, gimg_parts = [], [], []

        # per-image list states (images 0..n_list-1, same ordering as _unpack_into)
        dets_l = _bulk_to_host(self.detections)
        scores_l = _bulk_to_host(self.detection_scores)
        dlab_l = [l.reshape(-1) for l in _bulk_to_host(self.detection_labels)]
        gts_l = _bulk_to_host(self.groundtruths)
        glab_l = [l.reshape(-1) for l in _bulk_to_host(self.groundtruth_labels)]
        n_img = len(gts_l)
        if n_img:
            det_parts += [np.asarray(d).reshape(-1, 4) for d in dets_l]
            score_parts += [np.asarray(s).reshape(-1) for s in scores_l]
            dlab_parts += dlab_l
            dimg_parts.append(np.repeat(np.arange(n_img), [len(s) for s in dlab_l]))
            gt_parts += [np.asarray(g).reshape(-1, 4) for g in gts_l]
            glab_parts += glab_l
            gimg_parts.append(np.repeat(np.arange(n_img), [len(g) for g in glab_l]))

        # packed batch states: masked extraction, zero per-image Python work
        packed_p = _bulk_to_host(self.packed_preds)
        p_counts = _bulk_to_host(self.packed_pred_counts)
        packed_t = _bulk_to_host(self.packed_targets)
        t_counts = _bulk_to_host(self.packed_target_counts)
        for pp, pc, tt, tc in zip(packed_p, p_counts, packed_t, t_counts):
            _validate_packed_batch(pp, pc, tt, tc)
            b = pp.shape[0]
            pmask = np.arange(pp.shape[1]) < pc.reshape(-1, 1)
            tmask = np.arange(tt.shape[1]) < tc.reshape(-1, 1)
            det_parts.append(pp[..., :4][pmask])
            score_parts.append(pp[..., 4][pmask])
            dlab_parts.append(pp[..., 5][pmask].astype(np.int64))
            dimg_parts.append(np.broadcast_to((n_img + np.arange(b))[:, None], pmask.shape)[pmask])
            gt_parts.append(tt[..., :4][tmask])
            glab_parts.append(tt[..., 4][tmask].astype(np.int64))
            gimg_parts.append(np.broadcast_to((n_img + np.arange(b))[:, None], tmask.shape)[tmask])
            n_img += b

        cat = lambda parts, empty: np.concatenate(parts) if parts else empty  # noqa: E731
        det_boxes = cat(det_parts, np.zeros((0, 4)))
        det_scores = cat(score_parts, np.zeros(0))
        det_labels = cat(dlab_parts, np.zeros(0, np.int64)).astype(np.int64)
        det_img = cat(dimg_parts, np.zeros(0, np.int64))
        gt_boxes = cat(gt_parts, np.zeros((0, 4)))
        gt_labels = cat(glab_parts, np.zeros(0, np.int64)).astype(np.int64)
        gt_img = cat(gimg_parts, np.zeros(0, np.int64))

        if det_labels.size or gt_labels.size:
            classes = np.unique(np.concatenate([det_labels, gt_labels])).astype(int).tolist()
        else:
            classes = []
        sorted_ids = np.asarray(classes, dtype=np.int64)
        precisions, recalls = coco_eval_bbox(
            det_boxes,
            det_scores,
            det_img,
            np.searchsorted(sorted_ids, det_labels),
            gt_boxes,
            gt_img,
            np.searchsorted(sorted_ids, gt_labels),
            n_img,
            len(classes),
            np.asarray(self.iou_thresholds, dtype=np.float64),
            np.asarray(self.rec_thresholds),
            np.asarray(list(self.bbox_area_ranges.values()), dtype=np.float64),
            np.asarray(self.max_detection_thresholds, dtype=np.int64),
        )
        return self._finalize(precisions, recalls, classes)

    def _finalize(self, precisions: np.ndarray, recalls: np.ndarray, classes: List[int]) -> Dict[str, Array]:
        """Summarize precision/recall tensors into the COCO headline dict."""
        map_val, mar_val = self._summarize_results(precisions, recalls)

        map_per_class: Any = np.array([-1.0])
        mar_max_per_class: Any = np.array([-1.0])
        if self.class_metrics:
            map_list, mar_list = [], []
            for class_idx, _ in enumerate(classes):
                cls_prec = precisions[:, :, class_idx][:, :, None]
                cls_rec = recalls[:, class_idx][:, None]
                cls_map, cls_mar = self._summarize_results(cls_prec, cls_rec)
                map_list.append(cls_map["map"])
                mar_list.append(cls_mar[f"mar_{self.max_detection_thresholds[-1]}"])
            map_per_class = np.array(map_list, dtype=np.float32)
            mar_max_per_class = np.array(mar_list, dtype=np.float32)

        # dtype casts and squeezes happen in NUMPY, then one compile-free
        # device_put per value: jnp.asarray(..., dtype)/.squeeze() here would
        # trace + compile ~6 tiny XLA programs (~4 s cold) inside every fresh
        # process's first epoch-end compute
        metrics: Dict[str, Array] = {}
        metrics.update({k: jax.device_put(np.asarray(v, np.float32)) for k, v in map_val.items()})
        metrics.update({k: jax.device_put(np.asarray(v, np.float32)) for k, v in mar_val.items()})
        metrics["map_per_class"] = jax.device_put(np.asarray(map_per_class, np.float32).squeeze())
        metrics[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = jax.device_put(
            np.asarray(mar_max_per_class, np.float32).squeeze()
        )
        metrics["classes"] = jax.device_put(np.asarray(classes, np.int32).squeeze())
        return metrics

    def _evaluate_pair(
        self,
        idx: int,
        class_id: int,
        max_det: int,
        thresholds: np.ndarray,
        area_ranges: np.ndarray,
        dets: List[np.ndarray],
        det_scores: List[np.ndarray],
        det_labels: List[np.ndarray],
        gts: List[np.ndarray],
        gt_labels: List[np.ndarray],
    ) -> Optional[List[Dict[str, np.ndarray]]]:
        """Evaluate ONE (image, class) across every area range and IoU threshold.

        IoU is computed once (score-sorted rows, truncated to the largest max-det
        threshold, reference ``:412-450``); the greedy matching for all areas x
        thresholds runs in the native ``coco_match`` kernel (``native/match.cpp``,
        numpy fallback with identical pinned semantics). Returns one eval dict per
        area range, or None when the class is absent from the image.
        """
        gt_mask = gt_labels[idx] == class_id
        det_mask = det_labels[idx] == class_id
        n_gt = int(gt_mask.sum())
        n_det = int(det_mask.sum())
        if n_gt == 0 and n_det == 0:
            return None

        if n_det:
            scores = det_scores[idx][det_mask]
            order = np.argsort(-scores, kind="stable")[:max_det]
            scores_sorted = scores[order]
            det = _take(_take(dets[idx], det_mask), order)
            det_areas = _area(det, self.iou_type)
        else:
            scores_sorted = np.zeros(0)
            det = None
            det_areas = np.zeros(0)
        if n_gt:
            gt = _take(gts[idx], gt_mask)
            gt_areas = _area(gt, self.iou_type)
        else:
            gt = None
            gt_areas = np.zeros(0)

        if n_det and n_gt:
            iou_mat = _np_box_iou(det, gt) if self.iou_type == "bbox" else _np_mask_iou(det, gt)
        else:
            iou_mat = np.zeros((len(scores_sorted), n_gt))

        from torchmetrics_tpu.native import coco_match

        det_matches, det_ignore, gt_ignore = coco_match(
            iou_mat, det_areas, gt_areas, thresholds, area_ranges
        )
        return [
            {
                "dtMatches": det_matches[a],
                "dtScores": scores_sorted,
                "gtIgnore": gt_ignore[a],
                "dtIgnore": det_ignore[a],
            }
            for a in range(area_ranges.shape[0])
        ]

    def _calculate(
        self,
        class_ids: List[int],
        dets: List[np.ndarray],
        det_scores: List[np.ndarray],
        det_labels: List[np.ndarray],
        gts: List[np.ndarray],
        gt_labels: List[np.ndarray],
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Precision/recall accumulation over classes x areas x max-dets (reference ``:676-737``).

        COCO-scale design: a per-class image index skips the (image, class) pairs
        where the class appears on neither side — at 5k images x 80 classes that is
        the overwhelming majority — and each surviving pair is evaluated in one
        native-matcher call covering all areas and thresholds.
        """
        nb_imgs = len(gts)
        max_detections = self.max_detection_thresholds[-1]
        thresholds = np.asarray(self.iou_thresholds, dtype=np.float64)
        area_ranges = np.asarray(list(self.bbox_area_ranges.values()), dtype=np.float64)


        class_imgs: Dict[int, List[int]] = {c: [] for c in class_ids}
        for idx in range(nb_imgs):
            for c in np.union1d(det_labels[idx], gt_labels[idx]):
                if (c := int(c)) in class_imgs:
                    class_imgs[c].append(idx)

        nb_iou_thrs = len(self.iou_thresholds)
        nb_rec_thrs = len(self.rec_thresholds)
        nb_classes = len(class_ids)
        nb_areas = len(self.bbox_area_ranges)
        nb_max_det_thrs = len(self.max_detection_thresholds)
        precision = -np.ones((nb_iou_thrs, nb_rec_thrs, nb_classes, nb_areas, nb_max_det_thrs))
        recall = -np.ones((nb_iou_thrs, nb_classes, nb_areas, nb_max_det_thrs))

        rec_thresholds = np.asarray(self.rec_thresholds)

        for idx_cls, class_id in enumerate(class_ids):
            per_area: List[List[Dict[str, np.ndarray]]] = [[] for _ in range(nb_areas)]
            for img_id in class_imgs[class_id]:
                evals = self._evaluate_pair(
                    img_id, class_id, max_detections, thresholds, area_ranges,
                    dets, det_scores, det_labels, gts, gt_labels,
                )
                if evals is None:
                    continue
                for idx_area in range(nb_areas):
                    per_area[idx_area].append(evals[idx_area])
            for idx_area in range(nb_areas):
                if not per_area[idx_area]:
                    continue
                for idx_max_det, max_det in enumerate(self.max_detection_thresholds):
                    self._accumulate(
                        precision, recall, per_area[idx_area], rec_thresholds,
                        idx_cls, idx_area, idx_max_det, max_det,
                    )
        return precision, recall

    def _accumulate(
        self,
        precision: np.ndarray,
        recall: np.ndarray,
        evals: List[Dict[str, np.ndarray]],
        rec_thresholds: np.ndarray,
        idx_cls: int,
        idx_area: int,
        idx_max_det: int,
        max_det: int,
    ) -> None:
        """PR curve for one (class, area, max_det) cell (reference ``:773-844``)."""
        det_scores = np.concatenate([e["dtScores"][:max_det] for e in evals])
        # stable descending sort keeps COCO/Matlab tie order
        inds = np.argsort(-det_scores, kind="stable")
        det_scores_sorted = det_scores[inds]

        det_matches = np.concatenate([e["dtMatches"][:, :max_det] for e in evals], axis=1)[:, inds]
        det_ignore = np.concatenate([e["dtIgnore"][:, :max_det] for e in evals], axis=1)[:, inds]
        gt_ignore = np.concatenate([e["gtIgnore"] for e in evals])
        npig = int((~gt_ignore).sum())
        if npig == 0:
            return
        tps = det_matches & ~det_ignore
        fps = ~det_matches & ~det_ignore

        tp_sum = np.cumsum(tps, axis=1).astype(np.float64)
        fp_sum = np.cumsum(fps, axis=1).astype(np.float64)
        nb_rec_thrs = len(rec_thresholds)

        for idx_iou, (tp, fp) in enumerate(zip(tp_sum, fp_sum)):
            nd = len(tp)
            rc = tp / npig
            pr = tp / (fp + tp + np.finfo(np.float64).eps)
            recall[idx_iou, idx_cls, idx_area, idx_max_det] = rc[-1] if nd else 0

            # monotone envelope removes PR zigzags before interpolation
            pr = np.maximum.accumulate(pr[::-1])[::-1]

            inds_rec = np.searchsorted(rc, rec_thresholds, side="left")
            prec_at = np.zeros((nb_rec_thrs,))
            num_inds = int(inds_rec.argmax()) if inds_rec.max(initial=0) >= nd else nb_rec_thrs
            valid = inds_rec[:num_inds]
            prec_at[:num_inds] = pr[valid]
            precision[idx_iou, :, idx_cls, idx_area, idx_max_det] = prec_at

    def _summarize(
        self,
        results: Dict[str, np.ndarray],
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> np.ndarray:
        """Mean of the selected precision/recall cells, -1 when empty (reference ``:637-674``)."""
        area_inds = [i for i, k in enumerate(self.bbox_area_ranges.keys()) if k == area_range]
        mdet_inds = [i for i, k in enumerate(self.max_detection_thresholds) if k == max_dets]
        if avg_prec:
            prec = results["precision"]
            if iou_threshold is not None:
                thr = self.iou_thresholds.index(iou_threshold)
                prec = prec[thr, :, :, area_inds, mdet_inds]
            else:
                prec = prec[:, :, :, area_inds, mdet_inds]
        else:
            prec = results["recall"]
            if iou_threshold is not None:
                thr = self.iou_thresholds.index(iou_threshold)
                prec = prec[thr, :, area_inds, mdet_inds]
            else:
                prec = prec[:, :, area_inds, mdet_inds]
        valid = prec[prec > -1]
        return np.array(-1.0) if valid.size == 0 else valid.mean()

    def _summarize_results(
        self, precisions: np.ndarray, recalls: np.ndarray
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
        """The standard COCO headline numbers (reference ``:739-771``)."""
        results = {"precision": precisions, "recall": recalls}
        last_max_det = self.max_detection_thresholds[-1]
        map_val = {
            "map": self._summarize(results, True, max_dets=last_max_det),
            "map_50": (
                self._summarize(results, True, iou_threshold=0.5, max_dets=last_max_det)
                if 0.5 in self.iou_thresholds
                else np.array(-1.0)
            ),
            "map_75": (
                self._summarize(results, True, iou_threshold=0.75, max_dets=last_max_det)
                if 0.75 in self.iou_thresholds
                else np.array(-1.0)
            ),
            "map_small": self._summarize(results, True, area_range="small", max_dets=last_max_det),
            "map_medium": self._summarize(results, True, area_range="medium", max_dets=last_max_det),
            "map_large": self._summarize(results, True, area_range="large", max_dets=last_max_det),
        }
        mar_val = {f"mar_{max_det}": self._summarize(results, False, max_dets=max_det)
                   for max_det in self.max_detection_thresholds}
        mar_val["mar_small"] = self._summarize(results, False, area_range="small", max_dets=last_max_det)
        mar_val["mar_medium"] = self._summarize(results, False, area_range="medium", max_dets=last_max_det)
        mar_val["mar_large"] = self._summarize(results, False, area_range="large", max_dets=last_max_det)
        return map_val, mar_val

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
