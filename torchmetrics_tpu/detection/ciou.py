"""Modular CompleteIntersectionOverUnion (reference ``detection/ciou.py``)."""

from __future__ import annotations

from typing import Callable

from torchmetrics_tpu.detection.iou import IntersectionOverUnion
from torchmetrics_tpu.functional.detection.helpers import _box_ciou


class CompleteIntersectionOverUnion(IntersectionOverUnion):
    """Mean CIoU over matched boxes; invalid pairs get the reference's -2 floor."""

    _iou_type: str = "ciou"
    _invalid_val: float = -2.0
    _iou_kernel: Callable = staticmethod(_box_ciou)
