"""Modular CompleteIntersectionOverUnion (reference ``detection/ciou.py``)."""

from __future__ import annotations

from typing import Callable

from torchmetrics_tpu.detection.iou import IntersectionOverUnion
from torchmetrics_tpu.functional.detection.helpers import _box_ciou


class CompleteIntersectionOverUnion(IntersectionOverUnion):
    """Mean CIoU over matched boxes; invalid pairs get the reference's -2 floor.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = [{'boxes': jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), 'scores': jnp.asarray([0.9]), 'labels': jnp.asarray([0])}]
        >>> target = [{'boxes': jnp.asarray([[12.0, 10.0, 58.0, 62.0]]), 'labels': jnp.asarray([0])}]
        >>> from torchmetrics_tpu.detection.ciou import CompleteIntersectionOverUnion
        >>> metric = CompleteIntersectionOverUnion()
        >>> _ = metric.update(preds, target)
        >>> print({k: round(float(v), 4) for k, v in sorted(metric.compute().items())})
        {'ciou': 0.8871}
    """

    _iou_type: str = "ciou"
    _invalid_val: float = -2.0
    _iou_kernel: Callable = staticmethod(_box_ciou)
