"""Detection metrics (reference ``src/torchmetrics/detection/__init__.py``)."""

from torchmetrics_tpu.detection.ciou import CompleteIntersectionOverUnion
from torchmetrics_tpu.detection.diou import DistanceIntersectionOverUnion
from torchmetrics_tpu.detection.giou import GeneralizedIntersectionOverUnion
from torchmetrics_tpu.detection.ingraph import PackedMeanAveragePrecision
from torchmetrics_tpu.detection.iou import IntersectionOverUnion
from torchmetrics_tpu.detection.mean_ap import MeanAveragePrecision
from torchmetrics_tpu.detection.panoptic_qualities import ModifiedPanopticQuality, PanopticQuality

__all__ = [
    "CompleteIntersectionOverUnion",
    "DistanceIntersectionOverUnion",
    "GeneralizedIntersectionOverUnion",
    "IntersectionOverUnion",
    "MeanAveragePrecision",
    "ModifiedPanopticQuality",
    "PackedMeanAveragePrecision",
    "PanopticQuality",
]
