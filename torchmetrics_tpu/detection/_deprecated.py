"""Deprecated-root-import shims (reference ``detection/_deprecated.py``)."""

from torchmetrics_tpu.detection import (
    ModifiedPanopticQuality,
    PanopticQuality,
)
from torchmetrics_tpu.utilities.deprecation import root_alias

_ModifiedPanopticQuality = root_alias(ModifiedPanopticQuality, "detection")
_PanopticQuality = root_alias(PanopticQuality, "detection")
