"""Modular GeneralizedIntersectionOverUnion (reference ``detection/giou.py``)."""

from __future__ import annotations

from typing import Callable

from torchmetrics_tpu.detection.iou import IntersectionOverUnion
from torchmetrics_tpu.functional.detection.helpers import _box_giou


class GeneralizedIntersectionOverUnion(IntersectionOverUnion):
    """Mean GIoU over matched boxes; GIoU ranges in [-1, 1] so invalid pairs get -1.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = [{'boxes': jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), 'scores': jnp.asarray([0.9]), 'labels': jnp.asarray([0])}]
        >>> target = [{'boxes': jnp.asarray([[12.0, 10.0, 58.0, 62.0]]), 'labels': jnp.asarray([0])}]
        >>> from torchmetrics_tpu.detection.giou import GeneralizedIntersectionOverUnion
        >>> metric = GeneralizedIntersectionOverUnion()
        >>> _ = metric.update(preds, target)
        >>> print({k: round(float(v), 4) for k, v in sorted(metric.compute().items())})
        {'giou': 0.8843}
    """

    _iou_type: str = "giou"
    _invalid_val: float = -1.0
    _iou_kernel: Callable = staticmethod(_box_giou)
