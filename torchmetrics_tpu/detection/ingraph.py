"""In-graph packed-route mean-average-precision — the mAP hot path on device.

:class:`~torchmetrics_tpu.detection.mean_ap.MeanAveragePrecision` evaluates with
COCOeval semantics but runs its greedy best-GT matching and PR accumulation on
host numpy over ragged per-image lists — exactly the expensive part of a
detection eval epoch. This module lowers the *packed-array* update route (the
padded ``(B, M, ...)`` layout a batched NMS produces on device) to a single XLA
graph per step:

- **Padded per-image IoU**: one broadcasted ``(D, G)`` pairwise IoU per image,
  vmapped over the batch, label-masked so every class evaluates in the same
  pass.
- **Greedy assignment in-graph**: detections walk in score order under
  ``lax.fori_loop``; each step picks the best still-unmatched, non-ignored GT
  by masked argmax, vectorized over every IoU threshold × area range at once.
  Matching semantics are pinned to the host reference
  (``native/rle_mask.py::coco_match``): strict ``IoU > thr``, non-ignored GTs
  only, first-index tie-breaks.
- **Score-sorted PR accumulation as device histogram states**: instead of
  buffering per-image arrays for an epoch-end host sort, every detection folds
  its TP/FP verdict into fixed-shape per-``(class, threshold, area, maxdet)``
  score histograms (``score_bins`` bins over [0, 1]). ``compute()`` rebuilds
  the PR curves from the reversed-cumsum histograms fully in-graph — exact
  whenever distinct scores land in distinct bins, tolerance-bounded otherwise.

The states are plain sum-folded fixed-shape arrays, so the metric rides the
whole engine stack like a counter metric: donated compiled steps, power-of-two
batch buckets (``_engine_row_additive`` — a zero-count pad image contributes
nothing), the K-step scan queue, async drains, and ``class_axis`` sharding of
the leading class dim. The list/RLE route stays on
:class:`MeanAveragePrecision` (the retained host matcher, counted and
boundary-sanctioned); parity between the two is pinned by
``tests/test_heavy.py``.

Known deltas vs the host route, by construction: ``classes`` reports the full
configured ``[0, num_classes)`` range (presence is a data-dependent shape, and
absent classes are ``-1``-masked out of every mean exactly like the host
path), and per-class arrays are length ``num_classes``.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.engine import bucketing
from torchmetrics_tpu.functional.detection.helpers import _box_iou
from torchmetrics_tpu.metric import Metric

Array = jax.Array

# f64 under x64 (matches the host evaluator's float64 ingestion); f32 on TPU
_F64 = jnp.result_type(jnp.float32, jnp.float64)

#: host-reference epsilon in the precision denominator (``mean_ap.py:667``)
_PR_EPS = float(np.finfo(np.float64).eps)


class _MapParams(NamedTuple):
    """Static evaluation grid — hashable, closed over by the traced update."""

    num_classes: int
    iou_thresholds: Tuple[float, ...]
    rec_thresholds: Tuple[float, ...]
    max_dets: Tuple[int, ...]
    area_ranges: Tuple[Tuple[float, float], ...]
    score_bins: int


def _image_eval(p: Array, n_p: Array, t: Array, n_t: Array, params: _MapParams):
    """Match ONE padded image; return per-det verdicts + per-class GT counts.

    Mirrors ``coco_match``'s numpy fallback exactly: detections in stable
    score-descending order, masked argmax over valid same-class GTs that are
    neither matched nor area-ignored, strict ``IoU > thr``.
    """
    C = params.num_classes
    thr = jnp.asarray(params.iou_thresholds, dtype=_F64)          # (T,)
    areas = np.asarray(params.area_ranges, dtype=np.float64)      # (A, 2) static
    lo = jnp.asarray(areas[:, 0], dtype=_F64)
    hi = jnp.asarray(areas[:, 1], dtype=_F64)
    maxdets = np.asarray(params.max_dets)                         # (Md,) static
    T, A, Md = thr.shape[0], areas.shape[0], maxdets.shape[0]
    M, G = p.shape[0], t.shape[0]

    boxes_d = p[:, :4].astype(_F64)
    scores = p[:, 4]
    labels_d = p[:, 5].astype(jnp.int32)
    boxes_g = t[:, :4].astype(_F64)
    labels_g = t[:, 4].astype(jnp.int32)

    vd = (jnp.arange(M) < n_p) & (labels_d >= 0) & (labels_d < C)
    vg = (jnp.arange(G) < n_t) & (labels_g >= 0) & (labels_g < C)

    area_d = (boxes_d[:, 2] - boxes_d[:, 0]) * (boxes_d[:, 3] - boxes_d[:, 1])
    area_g = (boxes_g[:, 2] - boxes_g[:, 0]) * (boxes_g[:, 3] - boxes_g[:, 1])
    gt_ignore = (area_g[None, :] < lo[:, None]) | (area_g[None, :] > hi[:, None])  # (A, G)
    det_oor = (area_d[None, :] < lo[:, None]) | (area_d[None, :] > hi[:, None])    # (A, M)

    # per-class score rank (stable desc, original row order breaking ties) —
    # the per-(image, class) top-max_det truncation of the host route
    better = (scores[None, :] > scores[:, None]) | (
        (scores[None, :] == scores[:, None]) & (jnp.arange(M)[None, :] < jnp.arange(M)[:, None])
    )
    same_cls = labels_d[None, :] == labels_d[:, None]
    rank = jnp.sum(better & same_cls & vd[None, :], axis=1)
    participate = vd & (rank < int(maxdets[-1]))

    if G == 0 or M == 0:
        det_match = jnp.zeros((M, T, A), bool)
    else:
        # the SHARED jnp pairwise-IoU kernel (zero-union pairs define IoU as 0
        # — the same rule the host fallback's _safe_iou pins)
        iou = _box_iou(boxes_d, boxes_g)
        pair_ok = vd[:, None] & vg[None, :] & (labels_d[:, None] == labels_g[None, :])
        iou = jnp.where(pair_ok, iou, 0.0)
        order = jnp.argsort(-scores)  # stable: equal scores keep row order

        def body(k, carry):
            matched, det_match = carry
            d = order[k]
            allowed = (~matched) & (~gt_ignore[None, :, :]) & vg[None, None, :]  # (T, A, G)
            masked = jnp.where(allowed, iou[d][None, None, :], 0.0)
            g_best = jnp.argmax(masked, axis=-1)                                 # (T, A)
            v_best = jnp.take_along_axis(masked, g_best[..., None], axis=-1)[..., 0]
            hit = participate[d] & (v_best > thr[:, None])                       # (T, A)
            onehot = jax.nn.one_hot(g_best, G, dtype=bool)                       # (T, A, G)
            matched = matched | (onehot & hit[..., None])
            det_match = det_match.at[d].set(hit)
            return matched, det_match

        _, det_match = jax.lax.fori_loop(
            0, M, body, (jnp.zeros((T, A, G), bool), jnp.zeros((M, T, A), bool))
        )

    det_ign = (~det_match) & jnp.transpose(det_oor)[:, None, :]  # (M, T, A)

    incl = participate[:, None] & (rank[:, None] < jnp.asarray(maxdets)[None, :])  # (M, Md)
    tp = det_match & ~det_ign          # matched dets are never ignored — kept for clarity
    fp = (~det_match) & ~det_ign
    nb = params.score_bins
    bins = jnp.clip((scores * nb).astype(jnp.int32), 0, nb - 1)

    onehot_g = jax.nn.one_hot(labels_g, C, dtype=_F64) * vg[:, None].astype(_F64)  # (G, C)
    n_pos = ((~gt_ignore).astype(_F64) @ onehot_g).T                               # (C, A)
    return tp, fp, incl, bins, labels_d, n_pos


def packed_contributions(
    packed_preds: Array,
    pred_counts: Array,
    packed_targets: Array,
    target_counts: Array,
    params: _MapParams,
) -> Tuple[Array, Array, Array]:
    """Fold one padded batch into ``(tp_hist, fp_hist, n_pos)`` deltas.

    Pure and additive over the batch dim (each image contributes
    independently), so the engine's pad-subtract bucketing identity holds:
    a zero-count pad image contributes exactly zero to every state.
    """
    C, nb = params.num_classes, params.score_bins
    T = len(params.iou_thresholds)
    A = len(params.area_ranges)
    Md = len(params.max_dets)

    tp, fp, incl, bins, cls, n_pos = jax.vmap(
        lambda p, np_, t, nt: _image_eval(p, np_, t, nt, params)
    )(packed_preds, pred_counts, packed_targets, target_counts)

    # flatten every (image, det, threshold, area, maxdet) verdict into one
    # scatter-add over the flat histogram — invalid dets carry value 0
    val_tp = (tp[:, :, :, :, None] & incl[:, :, None, None, :]).astype(jnp.float32)  # (B,M,T,A,Md)
    val_fp = (fp[:, :, :, :, None] & incl[:, :, None, None, :]).astype(jnp.float32)
    c = jnp.clip(cls, 0, C - 1)[:, :, None, None, None]
    ti = jnp.arange(T)[None, None, :, None, None]
    ai = jnp.arange(A)[None, None, None, :, None]
    mi = jnp.arange(Md)[None, None, None, None, :]
    b = bins[:, :, None, None, None]
    idx = (((c * T + ti) * A + ai) * Md + mi) * nb + b
    flat = C * T * A * Md * nb
    tp_hist = jnp.zeros(flat, jnp.float32).at[idx.reshape(-1)].add(val_tp.reshape(-1))
    fp_hist = jnp.zeros(flat, jnp.float32).at[idx.reshape(-1)].add(val_fp.reshape(-1))
    shape = (C, T, A, Md, nb)
    return (
        tp_hist.reshape(shape),
        fp_hist.reshape(shape),
        n_pos.sum(axis=0).astype(jnp.float32),
    )


def _masked_mean(x: Array) -> Array:
    """Mean over cells > -1, or -1 when none are (the host ``_summarize`` rule)."""
    valid = x > -1
    count = valid.sum()
    total = jnp.where(valid, x, 0.0).sum()
    return jnp.where(count > 0, total / jnp.maximum(count, 1), -1.0).astype(jnp.float32)


def compute_from_hists(
    tp_hist: Array, fp_hist: Array, n_pos: Array, params: _MapParams
) -> Dict[str, Array]:
    """COCO headline dict from the device histogram states — one traceable graph.

    The reversed-bin cumsum IS the score-descending TP/FP accumulation of the
    host ``_accumulate``; the monotone envelope and the recall-threshold
    interpolation follow the same pinned rules (``searchsorted`` left,
    precision 0 past the achieved recall, cells -1 where ``n_pos`` is 0).
    """
    C, nb = params.num_classes, params.score_bins
    Md = len(params.max_dets)
    rec_t = jnp.asarray(params.rec_thresholds, dtype=_F64)

    tp_cum = jnp.cumsum(tp_hist[..., ::-1].astype(_F64), axis=-1)   # (C,T,A,Md,NB)
    fp_cum = jnp.cumsum(fp_hist[..., ::-1].astype(_F64), axis=-1)
    npig = n_pos.astype(_F64)[:, None, :, None]                     # (C,1,A,1)
    cell_ok = npig > 0
    rc = tp_cum / jnp.maximum(npig[..., None], 1.0)
    pr = tp_cum / (tp_cum + fp_cum + _PR_EPS)
    # monotone envelope (suffix running max — the host path's
    # ``np.maximum.accumulate(pr[::-1])[::-1]``)
    pr_env = jax.lax.cummax(pr, axis=pr.ndim - 1, reverse=True)

    # per-cell searchsorted (left) at the recall thresholds — vmapped over the
    # flattened cells so no (cells × R × NB) comparison tensor materializes
    idx = jax.vmap(lambda r: jnp.searchsorted(r, rec_t, side="left"))(
        rc.reshape(-1, nb)
    ).reshape(rc.shape[:-1] + (rec_t.shape[0],))                    # (C,T,A,Md,R)
    prec_at = jnp.where(
        idx < nb,
        jnp.take_along_axis(pr_env, jnp.clip(idx, 0, nb - 1), axis=-1),
        0.0,
    )
    precision = jnp.where(cell_ok[..., None], prec_at, -1.0)        # (C,T,A,Md,R)
    recall = jnp.where(cell_ok, tp_cum[..., -1] / jnp.maximum(npig, 1.0), -1.0)  # (C,T,A,Md)

    last = Md - 1
    iou_list = list(params.iou_thresholds)
    out: Dict[str, Array] = {
        "map": _masked_mean(precision[:, :, 0, last, :]),
        "map_small": _masked_mean(precision[:, :, 1, last, :]),
        "map_medium": _masked_mean(precision[:, :, 2, last, :]),
        "map_large": _masked_mean(precision[:, :, 3, last, :]),
    }
    for key, value in (("map_50", 0.5), ("map_75", 0.75)):
        out[key] = (
            _masked_mean(precision[:, iou_list.index(value), 0, last, :])
            if value in iou_list
            else jnp.asarray(-1.0, jnp.float32)
        )
    for mi, max_det in enumerate(params.max_dets):
        out[f"mar_{max_det}"] = _masked_mean(recall[:, :, 0, mi])
    out["mar_small"] = _masked_mean(recall[:, :, 1, last])
    out["mar_medium"] = _masked_mean(recall[:, :, 2, last])
    out["mar_large"] = _masked_mean(recall[:, :, 3, last])
    out["map_per_class"] = jax.vmap(_masked_mean)(precision[:, :, 0, last, :])
    out[f"mar_{params.max_dets[-1]}_per_class"] = jax.vmap(_masked_mean)(recall[:, :, 0, last])
    out["classes"] = jnp.arange(C, dtype=jnp.int32)
    return out


class PackedMeanAveragePrecision(Metric):
    """mAP/mAR over padded detection batches, evaluated entirely in-graph.

    The engine-native sibling of :class:`~torchmetrics_tpu.detection.mean_ap.
    MeanAveragePrecision` for the packed-array route: ``update`` takes the
    padded device layout directly and folds greedy matching + PR accumulation
    into fixed-shape histogram states in ONE compiled donated dispatch;
    ``compute`` rebuilds the COCO headline numbers from the histograms in one
    cached graph. Requires ``num_classes`` up front (fixed state shapes) and
    scores in ``[0, 1]``.

    Args:
        num_classes: class-id range ``[0, num_classes)``; out-of-range labels
            are treated as padding.
        box_format: input box convention (converted in-graph when not xyxy).
        iou_thresholds / rec_thresholds / max_detection_thresholds /
        class_metrics: as in :class:`MeanAveragePrecision`.
        score_bins: PR histogram resolution over [0, 1]; the curve is exact
            when distinct scores land in distinct bins.

    Use :meth:`update_batch` with the dict schema of the host packed route to
    get power-of-two padding of the detection-slot dims (stable compile
    signatures across ragged batches); the batch dim rides the engine's
    standard shape buckets.
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    # additive over batch images with all-sum states: bucketing + scan + async
    # compose like any counter metric (a count-0 pad image contributes zero)
    _engine_row_additive: bool = True
    # the class dim leads every state: a large-vocabulary detector's PR
    # histograms shard over the state mesh like any per-class counter
    _engine_shard_rules = {
        "map_tp_hist": "class_axis",
        "map_fp_hist": "class_axis",
        "map_n_pos": "class_axis",
    }

    def __init__(
        self,
        num_classes: int,
        box_format: str = "xyxy",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        score_bins: int = 1024,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_classes, int) or num_classes < 1:
            raise ValueError(f"Expected `num_classes` to be a positive int, got {num_classes!r}")
        if box_format not in ("xyxy", "xywh", "cxcywh"):
            raise ValueError(f"Expected `box_format` to be one of ('xyxy', 'xywh', 'cxcywh'), got {box_format}")
        if not isinstance(score_bins, int) or score_bins < 2:
            raise ValueError(f"Expected `score_bins` to be an int >= 2, got {score_bins!r}")
        self.box_format = box_format
        self.class_metrics = bool(class_metrics)
        iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, round((0.95 - 0.5) / 0.05) + 1).tolist()
        rec_thresholds = rec_thresholds or np.linspace(0.0, 1.00, round(1.00 / 0.01) + 1).tolist()
        max_dets = sorted(max_detection_thresholds or [1, 10, 100])
        # the host route's bbox_area_ranges, in the same order
        area_ranges = (
            (float(0**2), float(1e5**2)),
            (float(0**2), float(32**2)),
            (float(32**2), float(96**2)),
            (float(96**2), float(1e5**2)),
        )
        self._params = _MapParams(
            num_classes=num_classes,
            iou_thresholds=tuple(float(x) for x in iou_thresholds),
            rec_thresholds=tuple(float(x) for x in rec_thresholds),
            max_dets=tuple(int(x) for x in max_dets),
            area_ranges=area_ranges,
            score_bins=score_bins,
        )
        C, T, A, Md = num_classes, len(iou_thresholds), len(area_ranges), len(max_dets)
        hist = (C, T, A, Md, score_bins)
        self.add_state("map_tp_hist", jnp.zeros(hist, jnp.float32), dist_reduce_fx="sum")
        self.add_state("map_fp_hist", jnp.zeros(hist, jnp.float32), dist_reduce_fx="sum")
        self.add_state("map_n_pos", jnp.zeros((C, A), jnp.float32), dist_reduce_fx="sum")

    # ------------------------------------------------------------------ update

    def update(
        self,
        packed_preds: Array,
        pred_counts: Array,
        packed_targets: Array,
        target_counts: Array,
    ) -> None:
        """Fold one padded batch: ``(B, M, 6)`` preds / ``(B, G, 5)`` targets.

        Channel layout matches the host packed route: preds are
        ``[x1, y1, x2, y2, score, label]``, targets ``[x1, y1, x2, y2, label]``,
        with ``counts`` marking the valid prefix of each image's slots.
        Everything here is traceable jnp — the engine compiles it into one
        donated executable per (bucketed) shape signature.
        """
        pp = jnp.asarray(packed_preds, jnp.float32)
        tt = jnp.asarray(packed_targets, jnp.float32)
        if self.box_format != "xyxy":
            from torchmetrics_tpu.functional.detection.helpers import _box_convert

            b, m = pp.shape[:2]
            boxes_p = _box_convert(pp[..., :4].reshape(-1, 4), in_fmt=self.box_format, out_fmt="xyxy")
            pp = jnp.concatenate([boxes_p.reshape(b, m, 4), pp[..., 4:]], axis=-1)
            bt, g = tt.shape[:2]
            boxes_t = _box_convert(tt[..., :4].reshape(-1, 4), in_fmt=self.box_format, out_fmt="xyxy")
            tt = jnp.concatenate([boxes_t.reshape(bt, g, 4), tt[..., 4:]], axis=-1)
        tp, fp, n_pos = packed_contributions(
            pp,
            jnp.asarray(pred_counts, jnp.int32),
            tt,
            jnp.asarray(target_counts, jnp.int32),
            self._params,
        )
        self.map_tp_hist = self.map_tp_hist + tp
        self.map_fp_hist = self.map_fp_hist + fp
        self.map_n_pos = self.map_n_pos + n_pos

    def update_batch(self, preds: Dict[str, Any], target: Dict[str, Any]) -> None:
        """Dict-schema convenience: pack, width-bucket, then ``update``.

        Accepts the host packed route's schema (``boxes``/``scores``/``labels``/
        ``num_boxes``) and pads the detection-slot dims up to the next
        power-of-two bucket so ragged per-batch widths reuse O(log M) compile
        signatures instead of one per distinct width.
        """
        pp, pc, tt, tc = pack_detections(preds, target)
        self.update(pp, pc, tt, tc)

    # ------------------------------------------------------------------ compute

    def compute(self) -> Dict[str, Array]:
        """COCO headline dict from the histogram states (one cached graph)."""
        out = compute_from_hists(
            self.map_tp_hist, self.map_fp_hist, self.map_n_pos, self._params
        )
        if not self.class_metrics:
            out["map_per_class"] = jnp.asarray(-1.0, jnp.float32)
            out[f"mar_{self._params.max_dets[-1]}_per_class"] = jnp.asarray(-1.0, jnp.float32)
        return out

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


def pack_detections(
    preds: Dict[str, Any], target: Dict[str, Any], min_bucket: int = 8
) -> Tuple[Array, Array, Array, Array]:
    """Pack the dict schema into padded arrays with power-of-two slot widths.

    Validation mirrors the host route for host-side inputs (the f32 label
    exactness bound); added pad slots carry label ``-1`` so they can never
    alias class 0, and counts never cover them.
    """
    from torchmetrics_tpu.detection.mean_ap import _check_packed_label_bound

    for name, d, keys in (
        ("preds", preds, ("boxes", "scores", "labels", "num_boxes")),
        ("target", target, ("boxes", "labels", "num_boxes")),
    ):
        missing = [k for k in keys if k not in d]
        if missing:
            raise ValueError(f"Packed `{name}` dict is missing keys {missing}")
        lbl, cnt = d["labels"], d["num_boxes"]
        if isinstance(lbl, (np.ndarray, list, tuple)) and isinstance(cnt, (np.ndarray, list, tuple, int)):
            lbl_np = np.asarray(lbl)
            if lbl_np.ndim >= 2:
                # count range FIRST (same ordering as _validate_packed_batch): an
                # out-of-range count would make the label bound check — and the
                # valid-slot masks downstream — misread padding as real boxes
                cnt_np = np.asarray(cnt)
                if (cnt_np < 0).any() or (cnt_np > lbl_np.shape[-1]).any():
                    raise ValueError(
                        f"Packed `{name}` num_boxes out of range: counts must lie in"
                        f" [0, slot width] ({lbl_np.shape[-1]}) — a count past the"
                        " padding would silently count pad slots as real boxes"
                    )
                _check_packed_label_bound(name, lbl_np, cnt_np)

    # the PR histograms bin scores over [0, 1]: raw logits would silently
    # collapse into the extreme bins and degenerate the curve — host-side
    # inputs are checked here, device arrays carry the documented contract
    scores = preds["scores"]
    if isinstance(scores, (np.ndarray, list, tuple)) and isinstance(
        preds["num_boxes"], (np.ndarray, list, tuple, int)
    ):
        s = np.asarray(scores, dtype=np.float64)
        if s.ndim == 2:
            # slots past each image's count are padding and never read back
            valid = np.arange(s.shape[-1]) < np.asarray(preds["num_boxes"]).reshape(-1, 1)
            checked = s[valid]
            if checked.size and (float(checked.min()) < 0.0 or float(checked.max()) > 1.0):
                raise ValueError(
                    f"Packed scores must lie in [0, 1] (got"
                    f" [{float(checked.min())}, {float(checked.max())}]): the PR"
                    " histograms bin over the unit interval — apply a sigmoid/"
                    "normalization before packing"
                )

    p_boxes = jnp.asarray(preds["boxes"], jnp.float32)
    t_boxes = jnp.asarray(target["boxes"], jnp.float32)
    if p_boxes.ndim != 3 or p_boxes.shape[-1] != 4 or t_boxes.ndim != 3 or t_boxes.shape[-1] != 4:
        raise ValueError(f"Packed boxes must be (B, M, 4), got {p_boxes.shape} and {t_boxes.shape}")
    if p_boxes.shape[0] != t_boxes.shape[0]:
        raise ValueError("Packed preds and target must share the batch dimension")
    pp = jnp.concatenate(
        [
            p_boxes,
            jnp.asarray(preds["scores"], jnp.float32)[..., None],
            jnp.asarray(preds["labels"], jnp.float32)[..., None],
        ],
        axis=-1,
    )
    tt = jnp.concatenate([t_boxes, jnp.asarray(target["labels"], jnp.float32)[..., None]], axis=-1)

    def widen(arr: Array) -> Array:
        m = arr.shape[1]
        b = bucketing.next_bucket(max(m, 1), min_bucket)
        if b == m:
            return arr
        pad = jnp.full((arr.shape[0], b - m, arr.shape[2]), 0.0, arr.dtype)
        # pad slots get label -1 (never a valid class) in the last channel
        pad = pad.at[..., -1].set(-1.0)
        return jnp.concatenate([arr, pad], axis=1)

    return (
        widen(pp),
        jnp.asarray(preds["num_boxes"], jnp.int32),
        widen(tt),
        jnp.asarray(target["num_boxes"], jnp.int32),
    )
