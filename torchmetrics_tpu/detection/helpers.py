"""Input validation for detection metrics (reference ``detection/helpers.py``)."""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_ARRAY_TYPES = (jax.Array, np.ndarray)


def _input_validator(
    preds: Sequence[Dict[str, Array]], targets: Sequence[Dict[str, Array]], iou_type: str = "bbox"
) -> None:
    """Ensure the correct input format of ``preds`` and ``targets`` (reference ``helpers.py:19-70``)."""
    if iou_type == "bbox":
        item_val_name = "boxes"
    elif iou_type == "segm":
        item_val_name = "masks"
    else:
        raise Exception(f"IOU type {iou_type} is not supported")

    if not isinstance(preds, Sequence):
        raise ValueError(f"Expected argument `preds` to be of type Sequence, but got {preds}")
    if not isinstance(targets, Sequence):
        raise ValueError(f"Expected argument `target` to be of type Sequence, but got {targets}")
    if len(preds) != len(targets):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, but got {len(preds)} and {len(targets)}"
        )

    for k in [item_val_name, "scores", "labels"]:
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")

    for k in [item_val_name, "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    def _mask_ok(value) -> bool:
        if isinstance(value, _ARRAY_TYPES):
            return True
        # segm also accepts COCO-style *uncompressed* RLE dict sequences (native kernel
        # path): counts must be an integer run-length sequence, not pycocotools'
        # compressed bytes/str form — reject that here rather than deep in compute()
        return item_val_name == "masks" and isinstance(value, (list, tuple)) and all(
            isinstance(v, dict)
            and "size" in v
            and isinstance(v.get("counts"), (list, tuple, np.ndarray))
            for v in value
        )

    def _n(value) -> int:
        return len(value) if isinstance(value, (list, tuple)) else value.shape[0]

    if any(not _mask_ok(pred[item_val_name]) for pred in preds):
        raise ValueError(f"Expected all {item_val_name} in `preds` to be of type Array")
    if any(not isinstance(pred["scores"], _ARRAY_TYPES) for pred in preds):
        raise ValueError("Expected all scores in `preds` to be of type Array")
    if any(not isinstance(pred["labels"], _ARRAY_TYPES) for pred in preds):
        raise ValueError("Expected all labels in `preds` to be of type Array")
    if any(not _mask_ok(target[item_val_name]) for target in targets):
        raise ValueError(f"Expected all {item_val_name} in `target` to be of type Array")
    if any(not isinstance(target["labels"], _ARRAY_TYPES) for target in targets):
        raise ValueError("Expected all labels in `target` to be of type Array")

    for i, item in enumerate(targets):
        if _n(item[item_val_name]) != item["labels"].shape[0]:
            raise ValueError(
                f"Input {item_val_name} and labels of sample {i} in targets have a"
                f" different length (expected {_n(item[item_val_name])} labels, got {item['labels'].shape[0]})"
            )
    for i, item in enumerate(preds):
        if not (_n(item[item_val_name]) == item["labels"].shape[0] == item["scores"].shape[0]):
            raise ValueError(
                f"Input {item_val_name}, labels and scores of sample {i} in predictions have a"
                f" different length (expected {_n(item[item_val_name])} labels and scores,"
                f" got {item['labels'].shape[0]} labels and {item['scores'].shape[0]})"
            )


def _fix_empty_tensors(boxes: Array) -> Array:
    """Give degenerate empty box arrays a ``(0, 4)`` shape (reference ``helpers.py:73-77``)."""
    boxes = jnp.asarray(boxes)
    if boxes.size == 0 and boxes.ndim == 1:
        return boxes.reshape(0, 4)
    return boxes
