"""Modular Dice metric (reference ``classification/dice.py`` — legacy-format metric)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.dice import (
    _dice_compute,
    _dice_format,
    _dice_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.compute import _safe_divide

Array = jax.Array


class Dice(Metric):
    """Dice score with legacy auto-format inputs (reference ``dice.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        zero_division: float = 0.0,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = "global",
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed:
            raise ValueError(f"The `average` has to be one of {allowed}, got {average}.")
        if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
            raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
        if num_classes and ignore_index is not None and (not ignore_index < num_classes or num_classes == 1):
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")
        self.zero_division = zero_division
        self.num_classes = num_classes
        self.threshold = threshold
        self.average = average
        self.mdmc_average = mdmc_average
        self.ignore_index = ignore_index
        self.top_k = top_k
        self._samplewise = mdmc_average == "samplewise" or average == "samples"
        if self._samplewise:
            for name in ("tp", "fp", "fn"):
                self.add_state(name, [], dist_reduce_fx="cat")
        else:
            size = num_classes if num_classes else 2
            for name in ("tp", "fp", "fn"):
                self.add_state(name, jnp.zeros(size, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate tp/fp/fn counts."""
        preds_oh, target_oh = _dice_format(preds, target, self.threshold, self.top_k, self.num_classes)
        tp, fp, fn = _dice_update(
            preds_oh, target_oh, self.ignore_index, "samplewise" if self._samplewise else None
        )
        if self._samplewise:
            self.tp.append(tp)
            self.fp.append(fp)
            self.fn.append(fn)
        else:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.fn = self.fn + fn

    def compute(self) -> Array:
        """Averaged dice score."""
        from torchmetrics_tpu.utilities.data import dim_zero_cat

        tp = dim_zero_cat(self.tp) if isinstance(self.tp, list) else self.tp
        fp = dim_zero_cat(self.fp) if isinstance(self.fp, list) else self.fp
        fn = dim_zero_cat(self.fn) if isinstance(self.fn, list) else self.fn
        if self.mdmc_average == "samplewise" and self.average != "samples":
            per_sample = _safe_divide(2 * tp.sum(-1), 2 * tp.sum(-1) + fp.sum(-1) + fn.sum(-1), self.zero_division)
            return per_sample.mean()
        return _dice_compute(tp, fp, fn, average=self.average, zero_division=self.zero_division)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)
