"""Modular MatthewsCorrCoef metrics (reference ``classification/matthews_corrcoef.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from torchmetrics_tpu.functional.classification.matthews_corrcoef import _matthews_corrcoef_reduce
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryMatthewsCorrCoef(BinaryConfusionMatrix):
    """MCC for binary tasks (reference ``matthews_corrcoef.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def compute(self) -> Array:
        """MCC from the accumulated confmat."""
        return _matthews_corrcoef_reduce(self.confmat)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MulticlassMatthewsCorrCoef(MulticlassConfusionMatrix):
    """MCC for multiclass tasks (reference ``matthews_corrcoef.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def compute(self) -> Array:
        """MCC from the accumulated confmat."""
        return _matthews_corrcoef_reduce(self.confmat)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MultilabelMatthewsCorrCoef(MultilabelConfusionMatrix):
    """MCC for multilabel tasks (reference ``matthews_corrcoef.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels, threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def compute(self) -> Array:
        """MCC from the accumulated confmats."""
        return _matthews_corrcoef_reduce(self.confmat)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MatthewsCorrCoef:
    """Task router (reference ``matthews_corrcoef.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MatthewsCorrCoef
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> metric = MatthewsCorrCoef(task='binary')
        >>> print(round(float(metric(preds, target)), 4))
        0.5774
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryMatthewsCorrCoef(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassMatthewsCorrCoef(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelMatthewsCorrCoef(num_labels, threshold, **kwargs)
        raise ValueError(f"Not handled value: {task}")
