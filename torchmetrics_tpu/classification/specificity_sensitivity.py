"""Modular SpecificityAtSensitivity family (reference ``classification/specificity_sensitivity.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax

from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.specificity_sensitivity import (
    _binary_specificity_at_sensitivity_arg_validation,
    _binary_specificity_at_sensitivity_compute,
    _multiclass_specificity_at_sensitivity_arg_validation,
    _multiclass_specificity_at_sensitivity_compute,
    _multilabel_specificity_at_sensitivity_arg_validation,
    _multilabel_specificity_at_sensitivity_compute,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinarySpecificityAtSensitivity(BinaryPrecisionRecallCurve):
    """Max specificity at a minimum sensitivity, binary task (reference ``:46-127``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.classification.specificity_sensitivity import BinarySpecificityAtSensitivity
        >>> metric = BinarySpecificityAtSensitivity(min_sensitivity=0.5)
        >>> _ = metric.update(preds, target)
        >>> print(tuple(round(float(v), 4) for v in metric.compute()))
        (1.0, 0.75)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False

    def __init__(
        self,
        min_sensitivity: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds, ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_specificity_at_sensitivity_arg_validation(min_sensitivity, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_sensitivity = min_sensitivity

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(max specificity, threshold at that point)."""
        return _binary_specificity_at_sensitivity_compute(
            self._curve_state(), self.thresholds, self.min_sensitivity
        )


class MulticlassSpecificityAtSensitivity(MulticlassPrecisionRecallCurve):
    """Per-class max specificity at a minimum sensitivity (reference ``:129-223``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        min_sensitivity: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _multiclass_specificity_at_sensitivity_arg_validation(
                num_classes, min_sensitivity, thresholds, ignore_index
            )
        self.validate_args = validate_args
        self.min_sensitivity = min_sensitivity

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(per-class max specificity, per-class thresholds)."""
        return _multiclass_specificity_at_sensitivity_compute(
            self._curve_state(), self.num_classes, self.thresholds, self.min_sensitivity
        )


class MultilabelSpecificityAtSensitivity(MultilabelPrecisionRecallCurve):
    """Per-label max specificity at a minimum sensitivity (reference ``:225-321``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        min_sensitivity: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _multilabel_specificity_at_sensitivity_arg_validation(
                num_labels, min_sensitivity, thresholds, ignore_index
            )
        self.validate_args = validate_args
        self.min_sensitivity = min_sensitivity

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(per-label max specificity, per-label thresholds)."""
        return _multilabel_specificity_at_sensitivity_compute(
            self._curve_state(), self.num_labels, self.thresholds, self.ignore_index, self.min_sensitivity
        )


class SpecificityAtSensitivity:
    """Task router (reference ``:323-374``)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_sensitivity: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return BinarySpecificityAtSensitivity(min_sensitivity, thresholds, ignore_index, validate_args, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassSpecificityAtSensitivity(
                num_classes, min_sensitivity, thresholds, ignore_index, validate_args, **kwargs
            )
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelSpecificityAtSensitivity(
                num_labels, min_sensitivity, thresholds, ignore_index, validate_args, **kwargs
            )
        raise ValueError(f"Not handled value: {task}")
