"""Modular AUROC metrics (reference ``classification/auroc.py`` — ``BinaryAUROC(BinaryPrecisionRecallCurve):42``)."""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax

from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.auroc import (
    _binary_auroc_arg_validation,
    _binary_auroc_compute,
    _multiclass_auroc_arg_validation,
    _multiclass_auroc_compute,
    _multilabel_auroc_arg_validation,
    _multilabel_auroc_compute,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryAUROC(BinaryPrecisionRecallCurve):
    """AUROC for binary tasks (reference ``auroc.py:42-120``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        max_fpr: Optional[float] = None,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_auroc_arg_validation(max_fpr, thresholds, ignore_index)
        self.validate_args = validate_args
        self.max_fpr = max_fpr

    def compute(self) -> Array:
        """Area under the ROC curve."""
        return _binary_auroc_compute(self._curve_state(), self.thresholds, self.max_fpr)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MulticlassAUROC(MulticlassPrecisionRecallCurve):
    """AUROC for multiclass tasks (reference ``auroc.py:123-...``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs
        )
        if validate_args:
            _multiclass_auroc_arg_validation(num_classes, average, thresholds, ignore_index)
        self.validate_args = validate_args
        self.average = average

    def compute(self) -> Array:
        """Averaged per-class AUROC."""
        return _multiclass_auroc_compute(self._curve_state(), self.num_classes, self.average, self.thresholds)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MultilabelAUROC(MultilabelPrecisionRecallCurve):
    """AUROC for multilabel tasks (reference ``auroc.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        average: Optional[str] = "macro",
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs
        )
        if validate_args:
            _multilabel_auroc_arg_validation(num_labels, average, thresholds, ignore_index)
        self.validate_args = validate_args
        self.average = average

    def compute(self) -> Array:
        """Averaged per-label AUROC."""
        return _multilabel_auroc_compute(
            self._curve_state(), self.num_labels, self.average, self.thresholds, self.ignore_index
        )

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class AUROC:
    """Task router (reference ``auroc.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import AUROC
        >>> preds = jnp.asarray([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.asarray([0, 0, 1, 1, 1])
        >>> auroc = AUROC(task='binary')
        >>> print(float(auroc(preds, target)))
        0.5
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryAUROC(max_fpr, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassAUROC(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelAUROC(num_labels, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
