"""Modular PrecisionRecallCurve metrics (reference ``classification/precision_recall_curve.py``).

Dual-mode state (reference ``:142-151``): ``thresholds=None`` → unbounded ``preds`` /
``target`` cat-lists (exact curve, epoch-end host compute); ``thresholds`` given →
fixed ``(len_t, [C,] 2, 2)`` sum-reduced confusion tensor (binned curve, jit-safe,
TPU-preferred). AUROC / ROC / AveragePrecision subclass these and only change
``compute`` (reference ``auroc.py:42``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _adjust_threshold_arg,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryPrecisionRecallCurve(Metric):
    """PR curve for binary tasks (reference ``precision_recall_curve.py:54-182``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    preds: List[Array]
    target: List[Array]
    confmat: Array

    def __init__(
        self,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        if thresholds is None:
            self.thresholds = thresholds
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self.register_threshold_buffer(thresholds)
            self.add_state(
                "confmat", default=jnp.zeros((len(thresholds), 2, 2), dtype=jnp.int32), dist_reduce_fx="sum"
            )

    def register_threshold_buffer(self, thresholds: Array) -> None:
        self.thresholds = thresholds

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch in the active state mode."""
        if self.validate_args:
            _binary_precision_recall_curve_tensor_validation(preds, target, self.ignore_index)
        preds, target, _ = _binary_precision_recall_curve_format(preds, target, self.thresholds, self.ignore_index)
        state = _binary_precision_recall_curve_update(preds, target, self.thresholds)
        if isinstance(state, tuple):
            self.preds.append(state[0])
            self.target.append(state[1])
        else:
            self.confmat = self.confmat + state

    def _curve_state(self):
        return (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat

    def compute(self):
        """Final (precision, recall, thresholds)."""
        return _binary_precision_recall_curve_compute(self._curve_state(), self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("Recall", "Precision"))


class MulticlassPrecisionRecallCurve(Metric):
    """PR curves for multiclass tasks (reference ``precision_recall_curve.py:185-320``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    preds: List[Array]
    target: List[Array]
    confmat: Array

    def __init__(
        self,
        num_classes: int,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        if thresholds is None:
            self.thresholds = thresholds
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self.thresholds = thresholds
            self.add_state(
                "confmat",
                default=jnp.zeros((len(thresholds), num_classes, 2, 2), dtype=jnp.int32),
                dist_reduce_fx="sum",
            )

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch in the active state mode."""
        if self.validate_args:
            _multiclass_precision_recall_curve_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds, target, _ = _multiclass_precision_recall_curve_format(
            preds, target, self.num_classes, self.thresholds, self.ignore_index
        )
        state = _multiclass_precision_recall_curve_update(preds, target, self.num_classes, self.thresholds)
        if isinstance(state, tuple):
            self.preds.append(state[0])
            self.target.append(state[1])
        else:
            self.confmat = self.confmat + state

    def _curve_state(self):
        return (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat

    def compute(self):
        """Final per-class (precision, recall, thresholds)."""
        return _multiclass_precision_recall_curve_compute(self._curve_state(), self.num_classes, self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("Recall", "Precision"))


class MultilabelPrecisionRecallCurve(Metric):
    """PR curves for multilabel tasks (reference ``precision_recall_curve.py:323-460``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    preds: List[Array]
    target: List[Array]
    confmat: Array

    def __init__(
        self,
        num_labels: int,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        self.thresholds = thresholds
        if thresholds is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self.add_state(
                "confmat",
                default=jnp.zeros((len(thresholds), num_labels, 2, 2), dtype=jnp.int32),
                dist_reduce_fx="sum",
            )

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch in the active state mode."""
        if self.validate_args:
            _multilabel_precision_recall_curve_tensor_validation(preds, target, self.num_labels, self.ignore_index)
        preds, target, _ = _multilabel_precision_recall_curve_format(
            preds, target, self.num_labels, self.thresholds, self.ignore_index
        )
        state = _multilabel_precision_recall_curve_update(preds, target, self.num_labels, self.thresholds)
        if isinstance(state, tuple):
            self.preds.append(state[0])
            self.target.append(state[1])
        else:
            self.confmat = self.confmat + state

    def _curve_state(self):
        return (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat

    def compute(self):
        """Final per-label (precision, recall, thresholds)."""
        return _multilabel_precision_recall_curve_compute(
            self._curve_state(), self.num_labels, self.thresholds, self.ignore_index
        )

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("Recall", "Precision"))


class PrecisionRecallCurve:
    """Task router (reference ``precision_recall_curve.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import PrecisionRecallCurve
        >>> pred = jnp.asarray([0.0, 0.5, 0.7, 0.8])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> pr_curve = PrecisionRecallCurve(task='binary', thresholds=5)
        >>> precision, recall, thresholds = pr_curve(pred, target)
        >>> print(precision)
        [0.5       0.6666667 0.6666667 0.        0.        1.       ]
        >>> print(recall)
        [1. 1. 1. 0. 0. 0.]
        >>> print(thresholds)
        [0.   0.25 0.5  0.75 1.  ]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryPrecisionRecallCurve(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassPrecisionRecallCurve(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelPrecisionRecallCurve(num_labels, **kwargs)
        raise ValueError(f"Not handled value: {task}")
