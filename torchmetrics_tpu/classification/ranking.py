"""Modular multilabel ranking metrics (reference ``classification/ranking.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.confusion_matrix import _multilabel_confusion_matrix_format
from torchmetrics_tpu.functional.classification.ranking import (
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_coverage_error_update,
    _multilabel_ranking_average_precision_update,
    _multilabel_ranking_loss_update,
    _multilabel_ranking_tensor_validation,
    _ranking_reduce,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class _AbstractRanking(Metric):
    """Shared measure/total state plumbing (reference ``ranking.py`` modular classes)."""

    is_differentiable: bool = False
    full_state_update: bool = False

    measure: Array
    total: Array

    def __init__(
        self,
        num_labels: int,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_confusion_matrix_arg_validation(num_labels, threshold=0.0, ignore_index=ignore_index)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measure", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    _update_fn = None  # set in subclasses

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch."""
        if self.validate_args:
            _multilabel_ranking_tensor_validation(preds, target, self.num_labels, self.ignore_index)
        preds, target = _multilabel_confusion_matrix_format(
            preds, target, self.num_labels, threshold=0.0, ignore_index=self.ignore_index, should_threshold=False
        )
        measure, total = type(self)._update_fn(preds, target)
        self.measure = self.measure + measure
        self.total = self.total + total

    def compute(self) -> Array:
        """Averaged ranking measure."""
        return _ranking_reduce(self.measure, self.total)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MultilabelCoverageError(_AbstractRanking):
    """Coverage error (reference ``ranking.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> from torchmetrics_tpu.classification.ranking import MultilabelCoverageError
        >>> metric = MultilabelCoverageError(num_labels=3)
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        1.6667
    """

    higher_is_better: bool = False
    _update_fn = staticmethod(_multilabel_coverage_error_update)


class MultilabelRankingAveragePrecision(_AbstractRanking):
    """Label ranking average precision (reference ``ranking.py``)."""

    higher_is_better: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    _update_fn = staticmethod(_multilabel_ranking_average_precision_update)


class MultilabelRankingLoss(_AbstractRanking):
    """Label ranking loss (reference ``ranking.py``)."""

    higher_is_better: bool = False
    plot_lower_bound: float = 0.0
    _update_fn = staticmethod(_multilabel_ranking_loss_update)
