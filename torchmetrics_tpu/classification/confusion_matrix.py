"""Modular ConfusionMatrix metrics (reference ``src/torchmetrics/classification/confusion_matrix.py``).

State: one fixed-shape integer matrix, sum-reduced across chips — the most
TPU-friendly state layout possible (single psum at sync).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_compute,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_compute,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_confusion_matrix_compute,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_tensor_validation,
    _multilabel_confusion_matrix_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array



def _update_family(metric) -> tuple:
    """Identity of the state-producing update body for the CSE signature
    (the one shared keying rule — ``engine/statespec.update_family``): the
    kappa/jaccard/matthews derivatives inherit the confusion-matrix update
    verbatim and differ only in ``compute``, so they share the family."""
    from torchmetrics_tpu.engine.statespec import update_family

    return update_family(metric)


class BinaryConfusionMatrix(Metric):
    """2x2 confusion matrix for binary tasks (reference ``confusion_matrix.py``)."""

    is_differentiable = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    # engine shape-bucketing opt-in: zero pad rows bincount into fixed cells
    # whose contribution the compiled step subtracts (engine/bucketing.py)
    _engine_row_additive = True

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        normalize: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((2, 2), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch into the matrix."""
        if self.validate_args:
            _binary_confusion_matrix_tensor_validation(preds, target, self.ignore_index)
        preds, target = _binary_confusion_matrix_format(preds, target, self.threshold, self.ignore_index)
        self.confmat = self.confmat + _binary_confusion_matrix_update(preds, target)

    def _cse_signature(self):
        """Reduction signature (``engine/statespec.py``): ``normalize`` is
        compute-only — matrices with matching threshold/ignore_index share one
        canonical ``confmat`` reduction."""
        return (*_update_family(self), float(self.threshold), self.ignore_index)

    def compute(self) -> Array:
        """Final (normalized) matrix."""
        return _binary_confusion_matrix_compute(self.confmat, self.normalize)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        from torchmetrics_tpu.utilities.plot import plot_confusion_matrix

        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax)


class MulticlassConfusionMatrix(Metric):
    """CxC confusion matrix (reference ``confusion_matrix.py``)."""

    is_differentiable = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    # engine shape-bucketing opt-in: zero pad rows bincount into fixed cells
    # whose contribution the compiled step subtracts (engine/bucketing.py)
    _engine_row_additive = True
    # SPMD placement (parallel/sharding.py): the matrix rows (true-class axis)
    # partition over the state mesh — a num_classes x num_classes state holds
    # ~1/N rows per device, the class-axis unlock for matrices no one device
    # could hold. No active mesh (or indivisible num_classes) = replication.
    _engine_shard_rules = {"confmat": "class_axis"}

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        normalize: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize)
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch into the matrix."""
        if self.validate_args:
            _multiclass_confusion_matrix_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds, target = _multiclass_confusion_matrix_format(preds, target, self.ignore_index)
        self.confmat = self.confmat + _multiclass_confusion_matrix_update(preds, target, self.num_classes)

    def _cse_signature(self):
        """Reduction signature (``engine/statespec.py``): ``normalize`` is
        compute-only — matrices with matching num_classes/ignore_index share
        one canonical ``confmat`` reduction."""
        return (*_update_family(self), int(self.num_classes), self.ignore_index)

    def compute(self) -> Array:
        """Final (normalized) matrix."""
        return _multiclass_confusion_matrix_compute(self.confmat, self.normalize)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        from torchmetrics_tpu.utilities.plot import plot_confusion_matrix

        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax)


class MultilabelConfusionMatrix(Metric):
    """(L, 2, 2) per-label confusion matrices (reference ``confusion_matrix.py``)."""

    is_differentiable = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    # engine shape-bucketing opt-in: zero pad rows bincount into fixed cells
    # whose contribution the compiled step subtracts (engine/bucketing.py)
    _engine_row_additive = True
    # SPMD placement: the per-label (L, 2, 2) stack partitions its label axis
    # over the state mesh exactly like the per-class counters
    _engine_shard_rules = {"confmat": "class_axis"}

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        normalize: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize)
        self.num_labels = num_labels
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((num_labels, 2, 2), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch into the matrices."""
        if self.validate_args:
            _multilabel_confusion_matrix_tensor_validation(preds, target, self.num_labels, self.ignore_index)
        preds, target = _multilabel_confusion_matrix_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        self.confmat = self.confmat + _multilabel_confusion_matrix_update(preds, target, self.num_labels)

    def _cse_signature(self):
        """Reduction signature (``engine/statespec.py``): ``normalize`` is
        compute-only — matrices with matching num_labels/threshold/
        ignore_index share one canonical ``confmat`` reduction."""
        return (*_update_family(self), int(self.num_labels), float(self.threshold), self.ignore_index)

    def compute(self) -> Array:
        """Final (normalized) matrices."""
        return _multilabel_confusion_matrix_compute(self.confmat, self.normalize)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        from torchmetrics_tpu.utilities.plot import plot_confusion_matrix

        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax)


class ConfusionMatrix:
    """Task router (reference ``confusion_matrix.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ConfusionMatrix
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> confmat = ConfusionMatrix(task='binary')
        >>> print(confmat(preds, target))
        [[2 0]
         [1 1]]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        normalize: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"normalize": normalize, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryConfusionMatrix(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassConfusionMatrix(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelConfusionMatrix(num_labels, threshold, **kwargs)
        raise ValueError(f"Not handled value: {task}")
