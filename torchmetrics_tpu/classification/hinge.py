"""Modular HingeLoss metrics (reference ``classification/hinge.py``) — running sum + count states."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_format,
    _multiclass_confusion_matrix_format,
)
from torchmetrics_tpu.functional.classification.hinge import (
    _binary_hinge_loss_arg_validation,
    _binary_hinge_loss_tensor_validation,
    _binary_hinge_loss_update,
    _hinge_loss_compute,
    _multiclass_hinge_loss_arg_validation,
    _multiclass_hinge_loss_tensor_validation,
    _multiclass_hinge_loss_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTaskNoMultilabel

Array = jax.Array


class BinaryHingeLoss(Metric):
    """Hinge loss for binary tasks (reference ``hinge.py`` modular).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.classification.hinge import BinaryHingeLoss
        >>> metric = BinaryHingeLoss()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.8167
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    measures: Array
    total: Array

    def __init__(
        self,
        squared: bool = False,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_hinge_loss_arg_validation(squared, ignore_index)
        self.squared = squared
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measures", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate hinge measures."""
        if self.validate_args:
            _binary_hinge_loss_tensor_validation(preds, target, self.ignore_index)
        preds, target = _binary_confusion_matrix_format(
            preds, target, threshold=0.0, ignore_index=self.ignore_index, convert_to_labels=False
        )
        keep = np.asarray(target) >= 0
        if not keep.all():
            preds = jnp.asarray(np.asarray(preds)[keep])
            target = jnp.asarray(np.asarray(target)[keep])
        measures, total = _binary_hinge_loss_update(preds, target, self.squared)
        self.measures = self.measures + measures
        self.total = self.total + total

    def compute(self) -> Array:
        """Mean hinge loss."""
        return _hinge_loss_compute(self.measures, self.total)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MulticlassHingeLoss(Metric):
    """Hinge loss for multiclass tasks (reference ``hinge.py`` modular)."""

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    measures: Array
    total: Array

    def __init__(
        self,
        num_classes: int,
        squared: bool = False,
        multiclass_mode: str = "crammer-singer",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_hinge_loss_arg_validation(num_classes, squared, multiclass_mode, ignore_index)
        self.num_classes = num_classes
        self.squared = squared
        self.multiclass_mode = multiclass_mode
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state(
            "measures",
            jnp.asarray(0.0) if multiclass_mode == "crammer-singer" else jnp.zeros(num_classes),
            dist_reduce_fx="sum",
        )
        self.add_state("total", jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate hinge measures."""
        if self.validate_args:
            _multiclass_hinge_loss_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds, target = _multiclass_confusion_matrix_format(
            preds, target, ignore_index=self.ignore_index, convert_to_labels=False
        )
        keep = np.asarray(target) >= 0
        if not keep.all():
            preds = jnp.asarray(np.asarray(preds)[keep])
            target = jnp.asarray(np.asarray(target)[keep])
        measures, total = _multiclass_hinge_loss_update(preds, target, self.squared, self.multiclass_mode)
        self.measures = self.measures + measures
        self.total = self.total + total

    def compute(self) -> Array:
        """Mean hinge loss (per-class for one-vs-all)."""
        return _hinge_loss_compute(self.measures, self.total)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class HingeLoss:
    """Task router (reference ``hinge.py`` legacy class)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        num_classes: Optional[int] = None,
        squared: bool = False,
        multiclass_mode: str = "crammer-singer",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryHingeLoss(squared, **kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassHingeLoss(num_classes, squared, multiclass_mode, **kwargs)
        raise ValueError(f"Not handled value: {task}")
