"""Modular PrecisionAtFixedRecall family (reference ``classification/precision_fixed_recall.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax

from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.precision_fixed_recall import _precision_at_recall
from torchmetrics_tpu.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _binary_recall_at_fixed_precision_compute,
    _multiclass_recall_at_fixed_precision_arg_compute,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_arg_compute,
    _multilabel_recall_at_fixed_precision_arg_validation,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryPrecisionAtFixedRecall(BinaryPrecisionRecallCurve):
    """Max precision at a minimum recall, binary task (reference ``:44-172``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.classification.precision_fixed_recall import BinaryPrecisionAtFixedRecall
        >>> metric = BinaryPrecisionAtFixedRecall(min_recall=0.5)
        >>> _ = metric.update(preds, target)
        >>> print(tuple(round(float(v), 4) for v in metric.compute()))
        (1.0, 0.75)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        min_recall: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds, ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_recall_at_fixed_precision_arg_validation(min_recall, thresholds, ignore_index, arg_name="min_recall")
        self.validate_args = validate_args
        self.min_recall = min_recall

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(max precision, threshold at that point)."""
        return _binary_recall_at_fixed_precision_compute(
            self._curve_state(), self.thresholds, self.min_recall, reduce_fn=_precision_at_recall
        )


class MulticlassPrecisionAtFixedRecall(MulticlassPrecisionRecallCurve):
    """Per-class max precision at a minimum recall (reference ``:174-316``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        min_recall: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_recall, thresholds, ignore_index, arg_name="min_recall")
        self.validate_args = validate_args
        self.min_recall = min_recall

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(per-class max precision, per-class thresholds)."""
        return _multiclass_recall_at_fixed_precision_arg_compute(
            self._curve_state(), self.num_classes, self.thresholds, self.min_recall, reduce_fn=_precision_at_recall
        )


class MultilabelPrecisionAtFixedRecall(MultilabelPrecisionRecallCurve):
    """Per-label max precision at a minimum recall (reference ``:318-460``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        min_recall: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_recall, thresholds, ignore_index, arg_name="min_recall")
        self.validate_args = validate_args
        self.min_recall = min_recall

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(per-label max precision, per-label thresholds)."""
        return _multilabel_recall_at_fixed_precision_arg_compute(
            self._curve_state(), self.num_labels, self.thresholds, self.ignore_index, self.min_recall,
            reduce_fn=_precision_at_recall,
        )


class PrecisionAtFixedRecall:
    """Task router (reference ``:463-501``)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_recall: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return BinaryPrecisionAtFixedRecall(min_recall, thresholds, ignore_index, validate_args, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassPrecisionAtFixedRecall(
                num_classes, min_recall, thresholds, ignore_index, validate_args, **kwargs
            )
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelPrecisionAtFixedRecall(
                num_labels, min_recall, thresholds, ignore_index, validate_args, **kwargs
            )
        raise ValueError(f"Not handled value: {task}")
