"""Modular Specificity metrics (reference ``src/torchmetrics/classification/specificity.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.classification.precision_recall import _route_task
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.functional.classification.specificity import _specificity_reduce
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class BinarySpecificity(BinaryStatScores):
    """Specificity for binary tasks (reference ``specificity.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassSpecificity(MulticlassStatScores):
    """Specificity for multiclass tasks (reference ``specificity.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelSpecificity(MultilabelStatScores):
    """Specificity for multilabel tasks (reference ``specificity.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(
            tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


class Specificity:
    """Task router (reference ``specificity.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import Specificity
        >>> target = jnp.asarray([0, 1, 0, 1])
        >>> preds = jnp.asarray([0, 1, 1, 1])
        >>> metric = Specificity(task='binary')
        >>> print(float(metric(preds, target)))
        0.5
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        return _route_task(
            BinarySpecificity, MulticlassSpecificity, MultilabelSpecificity,
            task, threshold, num_classes, num_labels, average, multidim_average,
            top_k, ignore_index, validate_args, **kwargs,
        )
