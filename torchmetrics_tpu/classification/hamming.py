"""Modular Hamming distance metrics (reference ``src/torchmetrics/classification/hamming.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.classification.precision_recall import _route_task
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.functional.classification.hamming import _hamming_distance_reduce
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class BinaryHammingDistance(BinaryStatScores):
    """Hamming distance for binary tasks (reference ``hamming.py``)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassHammingDistance(MulticlassStatScores):
    """Hamming distance for multiclass tasks (reference ``hamming.py``)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelHammingDistance(MultilabelStatScores):
    """Hamming distance for multilabel tasks (reference ``hamming.py``)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(
            tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


class HammingDistance:
    """Task router (reference ``hamming.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import HammingDistance
        >>> target = jnp.asarray([[0, 1], [1, 1]])
        >>> preds = jnp.asarray([[0, 1], [0, 1]])
        >>> metric = HammingDistance(task='multilabel', num_labels=2)
        >>> print(float(metric(preds, target)))
        0.25
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        return _route_task(
            BinaryHammingDistance, MulticlassHammingDistance, MultilabelHammingDistance,
            task, threshold, num_classes, num_labels, average, multidim_average,
            top_k, ignore_index, validate_args, **kwargs,
        )
