"""Modular CalibrationError metrics (reference ``classification/calibration_error.py``).

List states of (confidences, accuracies); binning deferred to compute.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.calibration_error import (
    _binary_calibration_error_arg_validation,
    _binary_calibration_error_tensor_validation,
    _binary_calibration_error_update,
    _ce_compute,
    _multiclass_calibration_error_arg_validation,
    _multiclass_calibration_error_tensor_validation,
    _multiclass_calibration_error_update,
)
from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_format,
    _multiclass_confusion_matrix_format,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.enums import ClassificationTaskNoMultilabel

Array = jax.Array


class BinaryCalibrationError(Metric):
    """ECE for binary tasks (reference ``calibration_error.py`` modular; states ``:120-121``)."""

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    confidences: List[Array]
    accuracies: List[Array]

    def __init__(
        self,
        n_bins: int = 15,
        norm: str = "l1",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("confidences", [], dist_reduce_fx="cat")
        self.add_state("accuracies", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append batch confidence/accuracy streams."""
        if self.validate_args:
            _binary_calibration_error_tensor_validation(preds, target, self.ignore_index)
        preds, target = _binary_confusion_matrix_format(
            preds, target, threshold=0.0, ignore_index=self.ignore_index, convert_to_labels=False
        )
        keep = np.asarray(target) >= 0
        if not keep.all():
            preds = jnp.asarray(np.asarray(preds)[keep])
            target = jnp.asarray(np.asarray(target)[keep])
        confidences, accuracies = _binary_calibration_error_update(preds, target)
        self.confidences.append(confidences.astype(jnp.float32))
        self.accuracies.append(accuracies.astype(jnp.float32))

    def compute(self) -> Array:
        """Binned calibration error."""
        return _ce_compute(dim_zero_cat(self.confidences), dim_zero_cat(self.accuracies), self.n_bins, self.norm)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MulticlassCalibrationError(Metric):
    """Top-label ECE for multiclass tasks (reference ``calibration_error.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    confidences: List[Array]
    accuracies: List[Array]

    def __init__(
        self,
        num_classes: int,
        n_bins: int = 15,
        norm: str = "l1",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        self.num_classes = num_classes
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("confidences", [], dist_reduce_fx="cat")
        self.add_state("accuracies", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append batch confidence/accuracy streams."""
        if self.validate_args:
            _multiclass_calibration_error_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds, target = _multiclass_confusion_matrix_format(
            preds, target, ignore_index=self.ignore_index, convert_to_labels=False
        )
        keep = np.asarray(target) >= 0
        if not keep.all():
            preds = jnp.asarray(np.asarray(preds)[keep])
            target = jnp.asarray(np.asarray(target)[keep])
        confidences, accuracies = _multiclass_calibration_error_update(preds, target)
        self.confidences.append(confidences)
        self.accuracies.append(accuracies)

    def compute(self) -> Array:
        """Binned calibration error."""
        return _ce_compute(dim_zero_cat(self.confidences), dim_zero_cat(self.accuracies), self.n_bins, self.norm)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class CalibrationError:
    """Task router (reference ``calibration_error.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import CalibrationError
        >>> preds = jnp.asarray([0.25, 0.25, 0.55, 0.75, 0.75])
        >>> target = jnp.asarray([0, 0, 1, 1, 1])
        >>> metric = CalibrationError(task='binary', n_bins=2, norm='l1')
        >>> print(round(float(metric(preds, target)), 4))
        0.29
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        n_bins: int = 15,
        norm: str = "l1",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"n_bins": n_bins, "norm": norm, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryCalibrationError(**kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassCalibrationError(num_classes, **kwargs)
        raise ValueError(f"Not handled value: {task}")
