"""Modular Accuracy metrics (reference ``src/torchmetrics/classification/accuracy.py:30-553``).

Each class subclasses its StatScores variant — only ``compute`` differs (e.g.
``BinaryAccuracy.compute`` ≙ reference ``accuracy.py:99-102`` calling ``_accuracy_reduce``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.functional.classification.accuracy import _accuracy_reduce
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryAccuracy(BinaryStatScores):
    """Accuracy for binary tasks (reference ``accuracy.py:30-135``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> metric = BinaryAccuracy()
        >>> print(float(metric(preds, target)))
        0.6666666865348816
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        """(tp+tn)/(tp+tn+fp+fn) over accumulated state."""
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassAccuracy(MulticlassStatScores):
    """Accuracy for multiclass tasks (reference ``accuracy.py:138-280``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def compute(self) -> Array:
        """Averaged accuracy over accumulated state."""
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelAccuracy(MultilabelStatScores):
    """Accuracy for multilabel tasks (reference ``accuracy.py:283-430``).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def compute(self) -> Array:
        """Averaged accuracy over accumulated state."""
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(
            tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


class Accuracy:
    """Task router: returns the Binary/Multiclass/Multilabel variant (reference ``accuracy.py:433-553``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import Accuracy
        >>> target = jnp.asarray([0, 1, 2, 3])
        >>> preds = jnp.asarray([0, 2, 1, 3])
        >>> accuracy = Accuracy(task='multiclass', num_classes=4)
        >>> print(float(accuracy(preds, target)))
        0.5
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update(
            {"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args}
        )
        if task == ClassificationTask.BINARY:
            return BinaryAccuracy(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassAccuracy(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelAccuracy(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
