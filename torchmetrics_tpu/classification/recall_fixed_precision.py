"""Modular RecallAtFixedPrecision family (reference ``classification/recall_fixed_precision.py``).

Subclasses the PR-curve metrics: identical states, operating-point selection at compute.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax

from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _binary_recall_at_fixed_precision_compute,
    _multiclass_recall_at_fixed_precision_arg_compute,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_arg_compute,
    _multilabel_recall_at_fixed_precision_arg_validation,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryRecallAtFixedPrecision(BinaryPrecisionRecallCurve):
    """Max recall at a minimum precision, binary task (reference ``:46-176``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.classification.recall_fixed_precision import BinaryRecallAtFixedPrecision
        >>> metric = BinaryRecallAtFixedPrecision(min_precision=0.5)
        >>> _ = metric.update(preds, target)
        >>> print(tuple(round(float(v), 4) for v in metric.compute()))
        (1.0, 0.35)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        min_precision: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds, ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(max recall, threshold at that point)."""
        return _binary_recall_at_fixed_precision_compute(self._curve_state(), self.thresholds, self.min_precision)


class MulticlassRecallAtFixedPrecision(MulticlassPrecisionRecallCurve):
    """Per-class max recall at a minimum precision (reference ``:178-320``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_precision, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(per-class max recall, per-class thresholds)."""
        return _multiclass_recall_at_fixed_precision_arg_compute(
            self._curve_state(), self.num_classes, self.thresholds, self.min_precision
        )


class MultilabelRecallAtFixedPrecision(MultilabelPrecisionRecallCurve):
    """Per-label max recall at a minimum precision (reference ``:322-464``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        min_precision: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_precision, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        """(per-label max recall, per-label thresholds)."""
        return _multilabel_recall_at_fixed_precision_arg_compute(
            self._curve_state(), self.num_labels, self.thresholds, self.ignore_index, self.min_precision
        )


class RecallAtFixedPrecision:
    """Task router (reference ``:467-505``)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_precision: float,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return BinaryRecallAtFixedPrecision(min_precision, thresholds, ignore_index, validate_args, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassRecallAtFixedPrecision(
                num_classes, min_precision, thresholds, ignore_index, validate_args, **kwargs
            )
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelRecallAtFixedPrecision(
                num_labels, min_precision, thresholds, ignore_index, validate_args, **kwargs
            )
        raise ValueError(f"Not handled value: {task}")
