"""Modular F-beta / F1 metrics (reference ``src/torchmetrics/classification/f_beta.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.classification.precision_recall import _route_task
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.functional.classification.f_beta import _fbeta_reduce, _validate_beta
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryFBetaScore(BinaryStatScores):
    """F-beta for binary tasks (reference ``f_beta.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        beta: float,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            threshold=threshold,
            multidim_average=multidim_average,
            ignore_index=ignore_index,
            validate_args=False,
            **kwargs,
        )
        if validate_args:
            _validate_beta(beta)
        self.validate_args = validate_args
        self.beta = beta

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _fbeta_reduce(tp, fp, tn, fn, self.beta, average="binary", multidim_average=self.multidim_average)


class MulticlassFBetaScore(MulticlassStatScores):
    """F-beta for multiclass tasks (reference ``f_beta.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def __init__(
        self,
        beta: float,
        num_classes: int,
        top_k: int = 1,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            top_k=top_k,
            average=average,
            multidim_average=multidim_average,
            ignore_index=ignore_index,
            validate_args=False,
            **kwargs,
        )
        if validate_args:
            _validate_beta(beta)
        self.validate_args = validate_args
        self.beta = beta

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _fbeta_reduce(tp, fp, tn, fn, self.beta, average=self.average, multidim_average=self.multidim_average)


class MultilabelFBetaScore(MultilabelStatScores):
    """F-beta for multilabel tasks (reference ``f_beta.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def __init__(
        self,
        beta: float,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels,
            threshold=threshold,
            average=average,
            multidim_average=multidim_average,
            ignore_index=ignore_index,
            validate_args=False,
            **kwargs,
        )
        if validate_args:
            _validate_beta(beta)
        self.validate_args = validate_args
        self.beta = beta

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _fbeta_reduce(
            tp, fp, tn, fn, self.beta, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


class BinaryF1Score(BinaryFBetaScore):
    """F1 for binary tasks (reference ``f_beta.py``)."""

    def __init__(
        self,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            beta=1.0,
            threshold=threshold,
            multidim_average=multidim_average,
            ignore_index=ignore_index,
            validate_args=validate_args,
            **kwargs,
        )


class MulticlassF1Score(MulticlassFBetaScore):
    """F1 for multiclass tasks (reference ``f_beta.py``)."""

    def __init__(
        self,
        num_classes: int,
        top_k: int = 1,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            beta=1.0,
            num_classes=num_classes,
            top_k=top_k,
            average=average,
            multidim_average=multidim_average,
            ignore_index=ignore_index,
            validate_args=validate_args,
            **kwargs,
        )


class MultilabelF1Score(MultilabelFBetaScore):
    """F1 for multilabel tasks (reference ``f_beta.py``)."""

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            beta=1.0,
            num_labels=num_labels,
            threshold=threshold,
            average=average,
            multidim_average=multidim_average,
            ignore_index=ignore_index,
            validate_args=validate_args,
            **kwargs,
        )


class FBetaScore:
    """Task router (reference ``f_beta.py`` legacy class)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        beta: float = 1.0,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update(
            {"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args}
        )
        if task == ClassificationTask.BINARY:
            return BinaryFBetaScore(beta, threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassFBetaScore(beta, num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelFBetaScore(beta, num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")


class F1Score:
    """Task router (reference ``f_beta.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import F1Score
        >>> target = jnp.asarray([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.asarray([0, 2, 1, 0, 0, 1])
        >>> f1 = F1Score(task='multiclass', num_classes=3)
        >>> print(round(float(f1(preds, target)), 4))
        0.3333
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        return _route_task(
            BinaryF1Score, MulticlassF1Score, MultilabelF1Score,
            task, threshold, num_classes, num_labels, average, multidim_average,
            top_k, ignore_index, validate_args, **kwargs,
        )
