"""Modular classification metrics (reference ``src/torchmetrics/classification/__init__.py``)."""

from torchmetrics_tpu.classification.auroc import (
    AUROC,
    BinaryAUROC,
    MulticlassAUROC,
    MultilabelAUROC,
)
from torchmetrics_tpu.classification.average_precision import (
    AveragePrecision,
    BinaryAveragePrecision,
    MulticlassAveragePrecision,
    MultilabelAveragePrecision,
)
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
    PrecisionRecallCurve,
)
from torchmetrics_tpu.classification.roc import (
    ROC,
    BinaryROC,
    MulticlassROC,
    MultilabelROC,
)
from torchmetrics_tpu.classification.cohen_kappa import (
    BinaryCohenKappa,
    CohenKappa,
    MulticlassCohenKappa,
)
from torchmetrics_tpu.classification.jaccard import (
    BinaryJaccardIndex,
    JaccardIndex,
    MulticlassJaccardIndex,
    MultilabelJaccardIndex,
)
from torchmetrics_tpu.classification.matthews_corrcoef import (
    BinaryMatthewsCorrCoef,
    MatthewsCorrCoef,
    MulticlassMatthewsCorrCoef,
    MultilabelMatthewsCorrCoef,
)
from torchmetrics_tpu.classification.calibration_error import (
    BinaryCalibrationError,
    CalibrationError,
    MulticlassCalibrationError,
)
from torchmetrics_tpu.classification.dice import Dice
from torchmetrics_tpu.classification.exact_match import (
    ExactMatch,
    MulticlassExactMatch,
    MultilabelExactMatch,
)
from torchmetrics_tpu.classification.group_fairness import BinaryFairness, BinaryGroupStatRates
from torchmetrics_tpu.classification.hinge import BinaryHingeLoss, HingeLoss, MulticlassHingeLoss
from torchmetrics_tpu.classification.ranking import (
    MultilabelCoverageError,
    MultilabelRankingAveragePrecision,
    MultilabelRankingLoss,
)
from torchmetrics_tpu.classification.accuracy import (
    Accuracy,
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
)
from torchmetrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    ConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from torchmetrics_tpu.classification.f_beta import (
    BinaryF1Score,
    BinaryFBetaScore,
    F1Score,
    FBetaScore,
    MulticlassF1Score,
    MulticlassFBetaScore,
    MultilabelF1Score,
    MultilabelFBetaScore,
)
from torchmetrics_tpu.classification.hamming import (
    BinaryHammingDistance,
    HammingDistance,
    MulticlassHammingDistance,
    MultilabelHammingDistance,
)
from torchmetrics_tpu.classification.precision_recall import (
    BinaryPrecision,
    BinaryRecall,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelPrecision,
    MultilabelRecall,
    Precision,
    Recall,
)
from torchmetrics_tpu.classification.specificity import (
    BinarySpecificity,
    MulticlassSpecificity,
    MultilabelSpecificity,
    Specificity,
)
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
    StatScores,
)

from torchmetrics_tpu.classification.precision_fixed_recall import (
    BinaryPrecisionAtFixedRecall,
    MulticlassPrecisionAtFixedRecall,
    MultilabelPrecisionAtFixedRecall,
    PrecisionAtFixedRecall,
)
from torchmetrics_tpu.classification.recall_fixed_precision import (
    BinaryRecallAtFixedPrecision,
    MulticlassRecallAtFixedPrecision,
    MultilabelRecallAtFixedPrecision,
    RecallAtFixedPrecision,
)
from torchmetrics_tpu.classification.specificity_sensitivity import (
    BinarySpecificityAtSensitivity,
    MulticlassSpecificityAtSensitivity,
    MultilabelSpecificityAtSensitivity,
    SpecificityAtSensitivity,
)

__all__ = [
    "BinaryCalibrationError",
    "CalibrationError",
    "MulticlassCalibrationError",
    "Dice",
    "ExactMatch",
    "MulticlassExactMatch",
    "MultilabelExactMatch",
    "BinaryFairness",
    "BinaryGroupStatRates",
    "BinaryHingeLoss",
    "HingeLoss",
    "MulticlassHingeLoss",
    "MultilabelCoverageError",
    "MultilabelRankingAveragePrecision",
    "MultilabelRankingLoss",
    "BinaryCohenKappa",
    "CohenKappa",
    "MulticlassCohenKappa",
    "BinaryJaccardIndex",
    "JaccardIndex",
    "MulticlassJaccardIndex",
    "MultilabelJaccardIndex",
    "BinaryMatthewsCorrCoef",
    "MatthewsCorrCoef",
    "MulticlassMatthewsCorrCoef",
    "MultilabelMatthewsCorrCoef",
    "AUROC",
    "BinaryAUROC",
    "MulticlassAUROC",
    "MultilabelAUROC",
    "AveragePrecision",
    "BinaryAveragePrecision",
    "MulticlassAveragePrecision",
    "MultilabelAveragePrecision",
    "BinaryPrecisionRecallCurve",
    "MulticlassPrecisionRecallCurve",
    "MultilabelPrecisionRecallCurve",
    "PrecisionRecallCurve",
    "ROC",
    "BinaryROC",
    "MulticlassROC",
    "MultilabelROC",
    "Accuracy",
    "BinaryAccuracy",
    "MulticlassAccuracy",
    "MultilabelAccuracy",
    "BinaryConfusionMatrix",
    "ConfusionMatrix",
    "MulticlassConfusionMatrix",
    "MultilabelConfusionMatrix",
    "BinaryF1Score",
    "BinaryFBetaScore",
    "F1Score",
    "FBetaScore",
    "MulticlassF1Score",
    "MulticlassFBetaScore",
    "MultilabelF1Score",
    "MultilabelFBetaScore",
    "BinaryHammingDistance",
    "HammingDistance",
    "MulticlassHammingDistance",
    "MultilabelHammingDistance",
    "BinaryPrecision",
    "BinaryRecall",
    "MulticlassPrecision",
    "MulticlassRecall",
    "MultilabelPrecision",
    "MultilabelRecall",
    "Precision",
    "Recall",
    "BinarySpecificity",
    "MulticlassSpecificity",
    "MultilabelSpecificity",
    "Specificity",
    "BinaryStatScores",
    "MulticlassStatScores",
    "MultilabelStatScores",
    "StatScores",
    "BinaryPrecisionAtFixedRecall",
    "MulticlassPrecisionAtFixedRecall",
    "MultilabelPrecisionAtFixedRecall",
    "PrecisionAtFixedRecall",
    "BinaryRecallAtFixedPrecision",
    "MulticlassRecallAtFixedPrecision",
    "MultilabelRecallAtFixedPrecision",
    "RecallAtFixedPrecision",
    "BinarySpecificityAtSensitivity",
    "MulticlassSpecificityAtSensitivity",
    "MultilabelSpecificityAtSensitivity",
    "SpecificityAtSensitivity",
]
