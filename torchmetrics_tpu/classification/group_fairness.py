"""Modular group-fairness metrics (reference ``classification/group_fairness.py``).

State: per-group tp/fp/tn/fn sum tensors of fixed shape (num_groups,) — one psum each
at sync (reference ``_AbstractGroupStatScores:35``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.group_fairness import (
    _binary_groups_stat_scores,
    _compute_binary_demographic_parity,
    _compute_binary_equal_opportunity,
    _groups_reduce,
    _groups_stat_transform,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class _AbstractGroupStatScores(Metric):
    """Shared per-group counter states (reference ``group_fairness.py:35-52``)."""

    tp: Array
    fp: Array
    tn: Array
    fn: Array

    def _create_states(self, num_groups: int) -> None:
        default = lambda: jnp.zeros(num_groups, dtype=jnp.int32)  # noqa: E731
        for name in ("tp", "fp", "tn", "fn"):
            self.add_state(name, default(), dist_reduce_fx="sum")

    def _update_states(self, group_stats) -> None:
        self.tp = self.tp + jnp.stack([s[0] for s in group_stats])
        self.fp = self.fp + jnp.stack([s[1] for s in group_stats])
        self.tn = self.tn + jnp.stack([s[2] for s in group_stats])
        self.fn = self.fn + jnp.stack([s[3] for s in group_stats])


class BinaryGroupStatRates(_AbstractGroupStatScores):
    """Per-group stat rates (reference ``group_fairness.py:54-146``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_groups: int,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_groups, int) or num_groups < 2:
            raise ValueError(f"Expected argument `num_groups` to be an int larger than 1, but got {num_groups}")
        self.num_groups = num_groups
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_states(num_groups)

    def update(self, preds: Array, target: Array, groups: Array) -> None:
        """Accumulate per-group counters."""
        group_stats = _binary_groups_stat_scores(
            preds, target, groups, self.num_groups, self.threshold, self.ignore_index, self.validate_args
        )
        self._update_states(group_stats)

    def compute(self) -> Dict[str, Array]:
        """Per-group [tp, fp, tn, fn] rates."""
        group_stats = [(self.tp[i], self.fp[i], self.tn[i], self.fn[i]) for i in range(self.num_groups)]
        return _groups_reduce(group_stats)


class BinaryFairness(_AbstractGroupStatScores):
    """Demographic parity / equal opportunity (reference ``group_fairness.py:149-286``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_groups: int,
        task: str = "all",
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if task not in ["demographic_parity", "equal_opportunity", "all"]:
            raise ValueError(
                f"Expected argument `task` to either be ``demographic_parity``,"
                f"``equal_opportunity`` or ``all`` but got {task}."
            )
        if not isinstance(num_groups, int) or num_groups < 2:
            raise ValueError(f"Expected argument `num_groups` to be an int larger than 1, but got {num_groups}")
        self.task = task
        self.num_groups = num_groups
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_states(num_groups)

    def update(self, preds: Array, target: Optional[Array] = None, groups: Optional[Array] = None) -> None:
        """Accumulate per-group counters (``target`` ignored for demographic parity)."""
        if groups is None:
            raise ValueError("Expected argument `groups` to be provided")
        if self.task == "demographic_parity":
            if target is not None:
                from torchmetrics_tpu.utilities.prints import rank_zero_warn

                rank_zero_warn("The task demographic_parity does not require a target.", UserWarning)
            target = jnp.zeros(jnp.asarray(preds).shape, dtype=jnp.int32)
        group_stats = _binary_groups_stat_scores(
            preds, target, groups, self.num_groups, self.threshold, self.ignore_index, self.validate_args
        )
        self._update_states(group_stats)

    def compute(self) -> Dict[str, Array]:
        """Fairness ratios keyed by min/max group ids."""
        transformed = _groups_stat_transform(
            [(self.tp[i], self.fp[i], self.tn[i], self.fn[i]) for i in range(self.num_groups)]
        )
        out: Dict[str, Array] = {}
        if self.task in ("demographic_parity", "all"):
            out.update(_compute_binary_demographic_parity(**transformed))
        if self.task in ("equal_opportunity", "all"):
            out.update(_compute_binary_equal_opportunity(**transformed))
        return out
