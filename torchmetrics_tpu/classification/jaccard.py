"""Modular JaccardIndex metrics (reference ``classification/jaccard.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from torchmetrics_tpu.functional.classification.jaccard import _jaccard_index_reduce
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryJaccardIndex(BinaryConfusionMatrix):
    """IoU for binary tasks (reference ``jaccard.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def compute(self) -> Array:
        """IoU from the accumulated confmat."""
        return _jaccard_index_reduce(self.confmat, average="binary")

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MulticlassJaccardIndex(MulticlassConfusionMatrix):
    """IoU for multiclass tasks (reference ``jaccard.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, ignore_index, normalize=None, validate_args=validate_args, **kwargs)
        self.average = average

    def compute(self) -> Array:
        """IoU from the accumulated confmat."""
        return _jaccard_index_reduce(self.confmat, average=self.average, ignore_index=self.ignore_index)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MultilabelJaccardIndex(MultilabelConfusionMatrix):
    """IoU for multilabel tasks (reference ``jaccard.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels, threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)
        self.average = average

    def compute(self) -> Array:
        """IoU from the accumulated confmats."""
        return _jaccard_index_reduce(self.confmat, average=self.average, ignore_index=self.ignore_index)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class JaccardIndex:
    """Task router (reference ``jaccard.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import JaccardIndex
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> metric = JaccardIndex(task='binary')
        >>> print(round(float(metric(preds, target)), 4))
        0.5
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryJaccardIndex(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassJaccardIndex(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelJaccardIndex(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
