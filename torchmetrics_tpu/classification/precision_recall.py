"""Modular Precision/Recall metrics (reference ``src/torchmetrics/classification/precision_recall.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.functional.classification.precision_recall import _precision_recall_reduce
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryPrecision(BinaryStatScores):
    """Precision for binary tasks (reference ``precision_recall.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.classification.precision_recall import BinaryPrecision
        >>> metric = BinaryPrecision()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.6667
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce(
            "precision", tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average
        )


class MulticlassPrecision(MulticlassStatScores):
    """Precision for multiclass tasks (reference ``precision_recall.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce(
            "precision", tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average
        )


class MultilabelPrecision(MultilabelStatScores):
    """Precision for multilabel tasks (reference ``precision_recall.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce(
            "precision", tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


class BinaryRecall(BinaryStatScores):
    """Recall for binary tasks (reference ``precision_recall.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce(
            "recall", tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average
        )


class MulticlassRecall(MulticlassStatScores):
    """Recall for multiclass tasks (reference ``precision_recall.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce(
            "recall", tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average
        )


class MultilabelRecall(MultilabelStatScores):
    """Recall for multilabel tasks (reference ``precision_recall.py``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce(
            "recall", tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


def _route_task(
    binary_cls,
    multiclass_cls,
    multilabel_cls,
    task: str,
    threshold: float,
    num_classes: Optional[int],
    num_labels: Optional[int],
    average: Optional[str],
    multidim_average: str,
    top_k: Optional[int],
    ignore_index: Optional[int],
    validate_args: bool,
    **kwargs: Any,
) -> Metric:
    """Shared task-router body for StatScores-derived families."""
    task = ClassificationTask.from_str(task)
    kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
    if task == ClassificationTask.BINARY:
        return binary_cls(threshold, **kwargs)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_cls(num_classes, top_k, average, **kwargs)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_cls(num_labels, threshold, average, **kwargs)
    raise ValueError(f"Not handled value: {task}")


class Precision:
    """Task router (reference ``precision_recall.py`` legacy class)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        return _route_task(
            BinaryPrecision, MulticlassPrecision, MultilabelPrecision,
            task, threshold, num_classes, num_labels, average, multidim_average,
            top_k, ignore_index, validate_args, **kwargs,
        )


class Recall:
    """Task router (reference ``precision_recall.py`` legacy class)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        return _route_task(
            BinaryRecall, MulticlassRecall, MultilabelRecall,
            task, threshold, num_classes, num_labels, average, multidim_average,
            top_k, ignore_index, validate_args, **kwargs,
        )
