"""Modular StatScores metrics — the base classes of the classification family.

Capability parity: reference ``src/torchmetrics/classification/stat_scores.py``
(``_AbstractStatScores:42``, ``BinaryStatScores:85``, ``MulticlassStatScores:185``,
``MultilabelStatScores:329``, task router ``:467``). State design follows the
reference: 4 sum-reduced tensors for ``multidim_average="global"``, 4 cat-lists for
``"samplewise"`` (``stat_scores.py:44-61``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_compute,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_compute,
    _multiclass_stat_scores_format_update,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_compute,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class _AbstractStatScores(Metric):
    """Common tp/fp/tn/fn state plumbing (reference ``classification/stat_scores.py:42-82``)."""

    tp: Any
    fp: Any
    tn: Any
    fn: Any

    # engine shape-bucketing opt-in: the "global" update is additive over batch
    # rows onto sum-reduced states, so padded rows subtract cleanly (the engine
    # additionally requires every state to be sum-reduced, which excludes the
    # samplewise cat-list layout automatically)
    _engine_row_additive = True
    # SPMD placement (parallel/sharding.py): per-class counters partition
    # their class axis over the state mesh, so vocab-scale (million-class)
    # tp/fp/tn/fn hold ~1/N per device. Scalar micro counters and samplewise
    # cat lists degrade to replication automatically (the rule inspects the
    # registered default's shape); with no active mesh this is a no-op.
    _engine_shard_rules = {"tp": "class_axis", "fp": "class_axis", "tn": "class_axis", "fn": "class_axis"}

    def _create_state(self, size: int, multidim_average: str = "global") -> None:
        """Register the 4 counter states; tensors+sum for global, lists+cat for samplewise."""
        if multidim_average == "samplewise":
            default = list
            reduce_fx = "cat"
        else:
            default = lambda: jnp.zeros(size, dtype=jnp.int32)  # noqa: E731
            reduce_fx = "sum"
        for name in ("tp", "fp", "tn", "fn"):
            self.add_state(name, default(), dist_reduce_fx=reduce_fx)

    def _update_state(self, tp: Array, fp: Array, tn: Array, fn: Array) -> None:
        """Accumulate (add or append, reference ``stat_scores.py:63-72``)."""
        if self.multidim_average == "samplewise":
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)
        else:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn

    def _final_state(self):
        """Concatenate list states (reference ``stat_scores.py:74-82``)."""
        tp = dim_zero_cat(self.tp)
        fp = dim_zero_cat(self.fp)
        tn = dim_zero_cat(self.tn)
        fn = dim_zero_cat(self.fn)
        return tp, fp, tn, fn

    def _update_family(self) -> tuple:
        """Identity of the state-producing update body for the CSE signature
        (the one shared keying rule — ``engine/statespec.update_family``)."""
        from torchmetrics_tpu.engine.statespec import update_family

        return update_family(self)


class BinaryStatScores(_AbstractStatScores):
    """tp/fp/tn/fn for binary tasks (reference ``classification/stat_scores.py:85-182``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.classification.stat_scores import BinaryStatScores
        >>> metric = BinaryStatScores()
        >>> _ = metric.update(preds, target)
        >>> print([round(float(x), 4) for x in metric.compute()])
        [2.0, 1.0, 2.0, 1.0, 3.0]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        self.threshold = threshold
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=1, multidim_average=multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch."""
        if self.validate_args:
            _binary_stat_scores_tensor_validation(preds, target, self.multidim_average, self.ignore_index)
        preds, target = _binary_stat_scores_format(preds, target, self.threshold, self.ignore_index)
        tp, fp, tn, fn = _binary_stat_scores_update(preds, target, self.multidim_average)
        self._update_state(tp, fp, tn, fn)

    def _cse_signature(self):
        """Reduction signature (``engine/statespec.py``): the binary tp/fp/tn/fn
        reduction is identical for every member whose threshold/ignore_index
        match — the family's whole spread lives in ``compute``."""
        if self.multidim_average != "global":
            return None  # samplewise cat-list states don't CSE
        return (*self._update_family(), float(self.threshold), self.ignore_index)

    def compute(self) -> Array:
        """Final [tp, fp, tn, fn, support]."""
        tp, fp, tn, fn = self._final_state()
        return _binary_stat_scores_compute(tp, fp, tn, fn, self.multidim_average)


class MulticlassStatScores(_AbstractStatScores):
    """tp/fp/tn/fn for multiclass tasks (reference ``classification/stat_scores.py:185-326``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_classes: int,
        top_k: int = 1,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        self.num_classes = num_classes
        self.top_k = top_k
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(
            size=1 if (average == "micro" and top_k == 1) else num_classes, multidim_average=multidim_average
        )

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch."""
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(
                preds, target, self.num_classes, self.multidim_average, self.ignore_index
            )
        tp, fp, tn, fn = _multiclass_stat_scores_format_update(
            preds, target, self.num_classes, self.top_k, self.average, self.multidim_average, self.ignore_index
        )
        self._update_state(tp, fp, tn, fn)

    def _cse_signature(self):
        """Reduction signature (``engine/statespec.py``).

        ``average`` reaches the update ONLY as the micro-with-top-1 collapse
        (scalar counters instead of per-class) — macro/weighted/none all
        accumulate identical per-class tp/fp/tn/fn and differ purely in
        ``compute``, so they normalize to one ``"per-class"`` token and FUSE;
        ``num_classes``/``top_k``/``ignore_index`` genuinely shape the
        reduction and split the signature.
        """
        if self.multidim_average != "global":
            return None
        micro = self.average == "micro" and self.top_k == 1
        return (
            *self._update_family(),
            int(self.num_classes),
            int(self.top_k),
            "micro" if micro else "per-class",
            self.ignore_index,
        )

    def compute(self) -> Array:
        """Final stat scores with averaging applied."""
        tp, fp, tn, fn = self._final_state()
        return _multiclass_stat_scores_compute(tp, fp, tn, fn, self.average, self.multidim_average)


class MultilabelStatScores(_AbstractStatScores):
    """tp/fp/tn/fn for multilabel tasks (reference ``classification/stat_scores.py:329-464``)."""

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        self.num_labels = num_labels
        self.threshold = threshold
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=num_labels, multidim_average=multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch."""
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(
                preds, target, self.num_labels, self.multidim_average, self.ignore_index
            )
        preds, target = _multilabel_stat_scores_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, self.multidim_average)
        self._update_state(tp, fp, tn, fn)

    def _cse_signature(self):
        """Reduction signature (``engine/statespec.py``): the multilabel
        reduction never sees ``average`` at all — per-label tp/fp/tn/fn for
        every averaging mode, so the whole family fuses on matching
        ``num_labels``/``threshold``/``ignore_index``."""
        if self.multidim_average != "global":
            return None
        return (
            *self._update_family(),
            int(self.num_labels),
            float(self.threshold),
            self.ignore_index,
        )

    def compute(self) -> Array:
        """Final stat scores with averaging applied."""
        tp, fp, tn, fn = self._final_state()
        return _multilabel_stat_scores_compute(tp, fp, tn, fn, self.average, self.multidim_average)


class StatScores(_AbstractStatScores):
    """Task-routing wrapper whose ``__new__`` returns the task variant (reference ``stat_scores.py:467-520``)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update(
            {"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args}
        )
        if task == ClassificationTask.BINARY:
            return BinaryStatScores(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassStatScores(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelStatScores(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
