"""Modular ROC metrics (reference ``classification/roc.py``) — PR-curve subclasses, compute swapped."""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax

from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


class BinaryROC(BinaryPrecisionRecallCurve):
    """ROC for binary tasks (reference ``roc.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.classification.roc import BinaryROC
        >>> metric = BinaryROC(thresholds=5)
        >>> _ = metric.update(preds, target)
        >>> print(tuple(v.shape for v in metric.compute()))
        ((5,), (5,), (5,))
    """

    def compute(self):
        """(fpr, tpr, thresholds)."""
        return _binary_roc_compute(self._curve_state(), self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("False positive rate", "True positive rate"))


class MulticlassROC(MulticlassPrecisionRecallCurve):
    """ROC for multiclass tasks (reference ``roc.py``)."""

    def compute(self):
        """Per-class (fpr, tpr, thresholds)."""
        return _multiclass_roc_compute(self._curve_state(), self.num_classes, self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("False positive rate", "True positive rate"))


class MultilabelROC(MultilabelPrecisionRecallCurve):
    """ROC for multilabel tasks (reference ``roc.py``)."""

    def compute(self):
        """Per-label (fpr, tpr, thresholds)."""
        return _multilabel_roc_compute(self._curve_state(), self.num_labels, self.thresholds, self.ignore_index)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("False positive rate", "True positive rate"))


class ROC:
    """Task router (reference ``roc.py`` legacy class)."""

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Optional[Union[int, List[float], Array]] = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryROC(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassROC(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelROC(num_labels, **kwargs)
        raise ValueError(f"Not handled value: {task}")
