"""Modular CohenKappa metrics (reference ``classification/cohen_kappa.py``) — ConfusionMatrix subclasses."""

from __future__ import annotations

from typing import Any, Optional

import jax

from torchmetrics_tpu.classification.confusion_matrix import BinaryConfusionMatrix, MulticlassConfusionMatrix
from torchmetrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_reduce, _validate_weights
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.enums import ClassificationTaskNoMultilabel

Array = jax.Array


class BinaryCohenKappa(BinaryConfusionMatrix):
    """Kappa for binary tasks (reference ``cohen_kappa.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        weights: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(threshold, ignore_index, normalize=None, validate_args=False, **kwargs)
        if validate_args:
            _validate_weights(weights)
        self.weights = weights
        self.validate_args = validate_args

    def compute(self) -> Array:
        """Kappa from the accumulated confmat."""
        return _cohen_kappa_reduce(self.confmat, self.weights)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MulticlassCohenKappa(MulticlassConfusionMatrix):
    """Kappa for multiclass tasks (reference ``cohen_kappa.py``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        weights: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, ignore_index, normalize=None, validate_args=False, **kwargs)
        if validate_args:
            _validate_weights(weights)
        self.weights = weights
        self.validate_args = validate_args

    def compute(self) -> Array:
        """Kappa from the accumulated confmat."""
        return _cohen_kappa_reduce(self.confmat, self.weights)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class CohenKappa:
    """Task router (reference ``cohen_kappa.py`` legacy class).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import CohenKappa
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> metric = CohenKappa(task='binary')
        >>> print(float(metric(preds, target)))
        0.5
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        weights: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"weights": weights, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryCohenKappa(threshold, **kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassCohenKappa(num_classes, **kwargs)
        raise ValueError(f"Not handled value: {task}")
