// COCO-style run-length-encoded mask kernels (host-side native component).
//
// TPU-native equivalent of the pycocotools C mask ops the reference leans on for
// iou_type="segm" (reference ``detection/mean_ap.py:38,131`` via ``mask_utils``;
// SURVEY §2.12 "pycocotools RLE mask IoU (C) -> C++ RLE kernel (host)").
// Dense-mask IoU stays on-device as a flattened matmul; these kernels handle the
// compressed-RLE interchange format without materializing H*W pixels per mask.
//
// Layout: masks are encoded column-major (Fortran order), runs alternate
// background/foreground starting with background, matching the COCO spec.

#include <cstdint>
#include <cstring>

extern "C" {

// Encode a column-major uint8 mask of h*w pixels into alternating run lengths.
// Returns the number of runs written to `counts` (capacity must be >= h*w + 1).
int64_t rle_encode(const uint8_t* mask, int64_t h, int64_t w, uint32_t* counts) {
    const int64_t n = h * w;
    int64_t n_runs = 0;
    uint8_t current = 0;  // runs start with the background count (possibly 0)
    int64_t run = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (mask[i] != current) {
            counts[n_runs++] = (uint32_t)run;
            run = 0;
            current = mask[i];
        }
        ++run;
    }
    counts[n_runs++] = (uint32_t)run;
    return n_runs;
}

// Decode alternating run lengths back into a column-major uint8 mask.
void rle_decode(const uint32_t* counts, int64_t n_runs, uint8_t* mask, int64_t n) {
    int64_t pos = 0;
    uint8_t value = 0;
    for (int64_t r = 0; r < n_runs && pos < n; ++r) {
        int64_t len = counts[r];
        if (len > n - pos) len = n - pos;
        memset(mask + pos, value, (size_t)len);
        pos += len;
        value = !value;
    }
}

// Foreground pixel count of an encoding.
int64_t rle_area(const uint32_t* counts, int64_t n_runs) {
    int64_t area = 0;
    for (int64_t r = 1; r < n_runs; r += 2) area += counts[r];
    return area;
}

// Intersection of two encodings by merging their run lists — no decode, O(runs).
int64_t rle_intersection(const uint32_t* a, int64_t na, const uint32_t* b, int64_t nb) {
    int64_t ia = 0, ib = 0;          // current run index in a / b
    int64_t ra = (na > 0) ? (int64_t)a[0] : 0;  // pixels left in current run
    int64_t rb = (nb > 0) ? (int64_t)b[0] : 0;
    uint8_t va = 0, vb = 0;          // current run value
    int64_t inter = 0;
    while (ia < na && ib < nb) {
        // skip exhausted runs
        while (ra == 0 && ++ia < na) { ra = a[ia]; va = !va; }
        while (rb == 0 && ++ib < nb) { rb = b[ib]; vb = !vb; }
        if (ia >= na || ib >= nb) break;
        int64_t step = (ra < rb) ? ra : rb;
        if (va && vb) inter += step;
        ra -= step;
        rb -= step;
    }
    return inter;
}

// Pairwise IoU matrix between nd detection and ng ground-truth encodings.
// Encodings are packed: counts_flat holds all runs, offsets/lengths index them.
// iscrowd semantics follow COCO: for crowd gt, the union is just the detection area.
void rle_iou(const uint32_t* counts_flat,
             const int64_t* d_off, const int64_t* d_len, int64_t nd,
             const int64_t* g_off, const int64_t* g_len, int64_t ng,
             const uint8_t* g_iscrowd,
             double* out) {
    for (int64_t i = 0; i < nd; ++i) {
        const uint32_t* dc = counts_flat + d_off[i];
        int64_t da = rle_area(dc, d_len[i]);
        for (int64_t j = 0; j < ng; ++j) {
            const uint32_t* gc = counts_flat + g_off[j];
            int64_t ga = rle_area(gc, g_len[j]);
            int64_t inter = rle_intersection(dc, d_len[i], gc, g_len[j]);
            double uni = g_iscrowd && g_iscrowd[j] ? (double)da : (double)(da + ga - inter);
            out[i * ng + j] = uni > 0 ? (double)inter / uni : 0.0;
        }
    }
}

}  // extern "C"
