"""ctypes bindings + numpy fallback for the C++ RLE mask kernels.

API mirrors what the reference gets from ``pycocotools.mask`` (encode/decode/area/iou;
``detection/mean_ap.py:38``): RLE objects are ``{"size": [h, w], "counts": uint32
array}`` with column-major alternating background/foreground runs, uncompressed.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional, Sequence

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_COMPILE_ATTEMPTED = False
NATIVE_RLE_AVAILABLE = False

_SRCS = [
    os.path.join(os.path.dirname(__file__), "rle.cpp"),
    os.path.join(os.path.dirname(__file__), "match.cpp"),
]
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")


def _stale(so_path: str) -> bool:
    if not os.path.exists(so_path):
        return True
    try:
        so_mtime = os.path.getmtime(so_path)
        return any(os.path.getmtime(src) > so_mtime for src in _SRCS)
    except OSError:  # source-stripped install: a present .so is good as-is
        return False


def _compile_and_load() -> Optional[ctypes.CDLL]:
    so_path = os.path.join(_BUILD_DIR, "libnative.so")
    if _stale(so_path):
        tmp = None
        try:
            os.makedirs(_BUILD_DIR, exist_ok=True)
            # build into a temp file then rename: concurrent importers see all-or-nothing
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
            os.close(fd)
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, *_SRCS],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, so_path)
        except Exception as err:  # no toolchain / sandbox: numpy fallback takes over
            if tmp is not None and os.path.exists(tmp):
                os.unlink(tmp)
            print(f"torchmetrics_tpu: native RLE kernel unavailable ({err})", file=sys.stderr)
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None

    u32p = ctypes.POINTER(ctypes.c_uint32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.rle_encode.restype = ctypes.c_int64
    lib.rle_encode.argtypes = [u8p, ctypes.c_int64, ctypes.c_int64, u32p]
    lib.rle_decode.restype = None
    lib.rle_decode.argtypes = [u32p, ctypes.c_int64, u8p, ctypes.c_int64]
    lib.rle_area.restype = ctypes.c_int64
    lib.rle_area.argtypes = [u32p, ctypes.c_int64]
    lib.rle_iou.restype = None
    lib.rle_iou.argtypes = [u32p, i64p, i64p, ctypes.c_int64, i64p, i64p, ctypes.c_int64, u8p, f64p]
    lib.coco_match.restype = None
    lib.coco_match.argtypes = [
        f64p, f64p, f64p, ctypes.c_int64, ctypes.c_int64,
        f64p, ctypes.c_int64, f64p, ctypes.c_int64,
        u8p, u8p, u8p,
    ]
    lib.lcs_len.restype = ctypes.c_int64
    lib.lcs_len.argtypes = [i64p, ctypes.c_int64, i64p, ctypes.c_int64]
    lib.coco_eval_bbox.restype = None
    lib.coco_eval_bbox.argtypes = [
        f64p, f64p, i64p, i64p, ctypes.c_int64,
        f64p, i64p, i64p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64,
        f64p, ctypes.c_int64,
        f64p, ctypes.c_int64,
        f64p, ctypes.c_int64,
        i64p, ctypes.c_int64,
        f64p, f64p,
    ]
    return lib


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _COMPILE_ATTEMPTED, NATIVE_RLE_AVAILABLE
    if not _COMPILE_ATTEMPTED:
        _COMPILE_ATTEMPTED = True  # one attempt; failures stick to the numpy fallback
        _LIB = _compile_and_load()
        NATIVE_RLE_AVAILABLE = _LIB is not None
    return _LIB


def native_available() -> bool:
    """Whether the compiled C++ kernel is in use (compiles lazily on first query)."""
    return _lib() is not None


def _as_u32(counts) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(counts, dtype=np.uint32))


def rle_encode(mask: np.ndarray) -> Dict[str, object]:
    """Encode a binary (h, w) mask into a COCO-style uncompressed RLE dict."""
    mask = np.asarray(mask)
    if mask.ndim != 2:
        raise ValueError(f"Expected a 2D mask, got shape {mask.shape}")
    h, w = mask.shape
    col_major = np.asfortranarray(mask.astype(np.uint8)).reshape(-1, order="F")
    lib = _lib()
    if lib is not None:
        buf = np.empty(h * w + 1, dtype=np.uint32)
        flat = np.ascontiguousarray(col_major)
        n_runs = lib.rle_encode(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(h), ctypes.c_int64(w),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        counts = buf[:n_runs].copy()
    else:
        changes = np.flatnonzero(np.diff(col_major)) + 1
        boundaries = np.concatenate([[0], changes, [col_major.size]])
        counts = np.diff(boundaries).astype(np.uint32)
        if col_major.size and col_major[0] == 1:
            counts = np.concatenate([[np.uint32(0)], counts])
    return {"size": [int(h), int(w)], "counts": counts}


def rle_decode(rle: Dict[str, object]) -> np.ndarray:
    """Decode an RLE dict back into a binary (h, w) mask."""
    h, w = rle["size"]
    counts = _as_u32(rle["counts"])
    lib = _lib()
    if lib is not None:
        out = np.zeros(h * w, dtype=np.uint8)
        lib.rle_decode(
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ctypes.c_int64(len(counts)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(h * w),
        )
    else:
        values = np.zeros(len(counts), dtype=np.uint8)
        values[1::2] = 1
        out = np.repeat(values, counts.astype(np.int64))
        out = np.pad(out[: h * w], (0, max(0, h * w - out.size)))
    return out.reshape((h, w), order="F").astype(bool)


def rle_area(rle: Dict[str, object]) -> int:
    """Foreground pixel count."""
    counts = _as_u32(rle["counts"])
    lib = _lib()
    if lib is not None:
        return int(lib.rle_area(
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), ctypes.c_int64(len(counts))
        ))
    return int(counts[1::2].sum())


def rle_iou(
    det: Sequence[Dict[str, object]],
    gt: Sequence[Dict[str, object]],
    iscrowd: Optional[Sequence[bool]] = None,
) -> np.ndarray:
    """Pairwise IoU matrix between detection and ground-truth RLEs (COCO crowd rules)."""
    nd, ng = len(det), len(gt)
    if nd == 0 or ng == 0:
        return np.zeros((nd, ng))
    crowd = np.zeros(ng, dtype=np.uint8) if iscrowd is None else np.asarray(iscrowd, dtype=np.uint8)

    lib = _lib()
    if lib is not None:
        all_counts: List[np.ndarray] = [_as_u32(r["counts"]) for r in det] + [_as_u32(r["counts"]) for r in gt]
        offsets = np.zeros(len(all_counts) + 1, dtype=np.int64)
        np.cumsum([len(c) for c in all_counts], out=offsets[1:])
        flat = np.concatenate(all_counts) if all_counts else np.zeros(0, dtype=np.uint32)
        d_off = np.ascontiguousarray(offsets[:nd])
        d_len = np.ascontiguousarray(offsets[1 : nd + 1] - offsets[:nd])
        g_off = np.ascontiguousarray(offsets[nd:-1])
        g_len = np.ascontiguousarray(offsets[nd + 1 :] - offsets[nd:-1])
        out = np.zeros(nd * ng, dtype=np.float64)
        lib.rle_iou(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            d_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            d_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(nd),
            g_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            g_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(ng),
            crowd.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
        return out.reshape(nd, ng)

    # numpy fallback: decode and intersect densely
    d_masks = [rle_decode(r).reshape(-1) for r in det]
    g_masks = [rle_decode(r).reshape(-1) for r in gt]
    out = np.zeros((nd, ng))
    for i, dm in enumerate(d_masks):
        da = dm.sum()
        for j, gm in enumerate(g_masks):
            inter = np.logical_and(dm, gm).sum()
            union = da if crowd[j] else da + gm.sum() - inter
            out[i, j] = inter / union if union > 0 else 0.0
    return out


def coco_match(
    iou: np.ndarray,
    det_areas: np.ndarray,
    gt_areas: np.ndarray,
    thresholds: np.ndarray,
    area_ranges: np.ndarray,
):
    """Greedy COCO matching for one (image, class) over ALL areas x thresholds.

    Args:
        iou: ``(D, G)`` with rows score-sorted (stable desc) and truncated to the
            largest max-det threshold; columns in original gt order.
        det_areas / gt_areas: per-box (or per-mask) areas.
        thresholds: ``(T,)`` IoU thresholds.
        area_ranges: ``(A, 2)`` [lo, hi] pairs.

    Returns:
        ``(det_matches, det_ignore, gt_ignore)`` with shapes ``(A, T, D)`` /
        ``(A, T, D)`` / ``(A, G)`` bool; gt flags are in the per-area partitioned
        order (in-range gts first). Semantics identical to the numpy fallback —
        see ``match.cpp`` for the pinned rules.

    Threshold convention: a detection matches only when ``IoU > thr`` (STRICT),
    in both the C++ kernel and the numpy fallback below. pycocotools instead
    admits IoUs exactly at the threshold (``iou >= thr - 1e-10``) and lets
    crowd gts match after real gts are exhausted; the divergence is observable
    only at exact-threshold IoUs (e.g. integer boxes at thr 0.5) and is pinned
    by ``tests/detection/test_native_eval_parity.py`` — see the ``match.cpp``
    header for the full rationale and the alignment recipe should parity at
    the boundary ever be required.
    """
    iou = np.ascontiguousarray(iou, dtype=np.float64)
    det_areas = np.ascontiguousarray(det_areas, dtype=np.float64)
    gt_areas = np.ascontiguousarray(gt_areas, dtype=np.float64)
    thresholds = np.ascontiguousarray(thresholds, dtype=np.float64)
    area_ranges = np.ascontiguousarray(area_ranges, dtype=np.float64)
    d, g = det_areas.shape[0], gt_areas.shape[0]
    t, a = thresholds.shape[0], area_ranges.shape[0]

    lib = _lib()
    if lib is not None:
        det_matches = np.zeros((a, t, d), dtype=np.uint8)
        det_ignore = np.zeros((a, t, d), dtype=np.uint8)
        gt_ignore = np.zeros((a, g), dtype=np.uint8)
        f64p = ctypes.POINTER(ctypes.c_double)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.coco_match(
            iou.ctypes.data_as(f64p),
            det_areas.ctypes.data_as(f64p),
            gt_areas.ctypes.data_as(f64p),
            ctypes.c_int64(d), ctypes.c_int64(g),
            thresholds.ctypes.data_as(f64p), ctypes.c_int64(t),
            area_ranges.ctypes.data_as(f64p), ctypes.c_int64(a),
            det_matches.ctypes.data_as(u8p),
            det_ignore.ctypes.data_as(u8p),
            gt_ignore.ctypes.data_as(u8p),
        )
        return det_matches.astype(bool), det_ignore.astype(bool), gt_ignore.astype(bool)

    # numpy fallback — the reference's loop semantics (mean_ap.py:510-635)
    det_matches = np.zeros((a, t, d), dtype=bool)
    det_ignore = np.zeros((a, t, d), dtype=bool)
    gt_ignore_out = np.zeros((a, g), dtype=bool)
    for ai, (lo, hi) in enumerate(area_ranges):
        ignore = (gt_areas < lo) | (gt_areas > hi)
        gtind = np.argsort(ignore.astype(np.uint8), kind="stable")
        gt_ign = ignore[gtind]
        gt_ignore_out[ai] = gt_ign
        iou_s = iou[:, gtind] if iou.size else iou
        for ti, thr in enumerate(thresholds):
            gt_matched = np.zeros(g, dtype=bool)
            for di in range(d):
                masked = iou_s[di] * ~(gt_matched | gt_ign)
                if masked.size == 0:
                    continue
                m = int(masked.argmax())
                if masked[m] <= thr:
                    continue
                det_matches[ai, ti, di] = True
                gt_matched[m] = True
        out_of_range = (det_areas < lo) | (det_areas > hi)
        det_ignore[ai] |= ~det_matches[ai] & out_of_range[None, :]
    return det_matches, det_ignore, gt_ignore_out


def coco_eval_bbox_available() -> bool:
    """Whether the epoch-level C++ bbox evaluator is usable."""
    return _lib() is not None


def coco_eval_bbox(
    det_boxes: np.ndarray,
    det_scores: np.ndarray,
    det_img: np.ndarray,
    det_cls: np.ndarray,
    gt_boxes: np.ndarray,
    gt_img: np.ndarray,
    gt_cls: np.ndarray,
    n_img: int,
    n_cls: int,
    iou_thrs: np.ndarray,
    rec_thrs: np.ndarray,
    area_ranges: np.ndarray,
    max_dets: np.ndarray,
):
    """Epoch-level COCO bbox evaluation — the whole accumulate stage in one C++ call.

    Args:
        det_boxes/gt_boxes: ``(N, 4)`` xyxy epoch concatenations.
        det_scores: ``(Nd,)``.
        det_img/gt_img: ``(N,)`` image indices in ``[0, n_img)``.
        det_cls/gt_cls: ``(N,)`` class INDICES in ``[0, n_cls)`` (pre-mapped).
        iou_thrs/rec_thrs: threshold grids; area_ranges ``(A, 2)``;
        max_dets: ascending max-detection thresholds (last = truncation cap).

    Returns:
        ``(precision, recall)`` with shapes ``(T, R, C, A, M)`` / ``(T, C, A, M)``,
        cells untouched by data at ``-1`` — identical semantics to the Python
        ``_calculate``/``_accumulate`` path in ``detection/mean_ap.py``.
    """
    lib = _lib()
    if lib is None:
        raise RuntimeError("native coco_eval_bbox requires the compiled kernel")
    det_boxes = np.ascontiguousarray(det_boxes.reshape(-1, 4), dtype=np.float64)
    gt_boxes = np.ascontiguousarray(gt_boxes.reshape(-1, 4), dtype=np.float64)
    det_scores = np.ascontiguousarray(det_scores, dtype=np.float64)
    det_img = np.ascontiguousarray(det_img, dtype=np.int64)
    det_cls = np.ascontiguousarray(det_cls, dtype=np.int64)
    gt_img = np.ascontiguousarray(gt_img, dtype=np.int64)
    gt_cls = np.ascontiguousarray(gt_cls, dtype=np.int64)
    iou_thrs = np.ascontiguousarray(iou_thrs, dtype=np.float64)
    rec_thrs = np.ascontiguousarray(rec_thrs, dtype=np.float64)
    area_ranges = np.ascontiguousarray(area_ranges, dtype=np.float64)
    max_dets = np.ascontiguousarray(max_dets, dtype=np.int64)

    t, r, a, m = len(iou_thrs), len(rec_thrs), area_ranges.shape[0], len(max_dets)
    precision = -np.ones((t, r, n_cls, a, m), dtype=np.float64)
    recall = -np.ones((t, n_cls, a, m), dtype=np.float64)

    f64p = ctypes.POINTER(ctypes.c_double)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.coco_eval_bbox(
        det_boxes.ctypes.data_as(f64p),
        det_scores.ctypes.data_as(f64p),
        det_img.ctypes.data_as(i64p),
        det_cls.ctypes.data_as(i64p),
        ctypes.c_int64(det_scores.shape[0]),
        gt_boxes.ctypes.data_as(f64p),
        gt_img.ctypes.data_as(i64p),
        gt_cls.ctypes.data_as(i64p),
        ctypes.c_int64(gt_img.shape[0]),
        ctypes.c_int64(n_img), ctypes.c_int64(n_cls),
        iou_thrs.ctypes.data_as(f64p), ctypes.c_int64(t),
        rec_thrs.ctypes.data_as(f64p), ctypes.c_int64(r),
        area_ranges.ctypes.data_as(f64p), ctypes.c_int64(a),
        max_dets.ctypes.data_as(i64p), ctypes.c_int64(m),
        precision.ctypes.data_as(f64p),
        recall.ctypes.data_as(f64p),
    )
    return precision, recall


def lcs_len(a_ids: np.ndarray, b_ids: np.ndarray) -> Optional[int]:
    """LCS length over int64 token-id sequences, or None when the kernel is absent."""
    lib = _lib()
    if lib is None:
        return None
    a = np.ascontiguousarray(a_ids, dtype=np.int64)
    b = np.ascontiguousarray(b_ids, dtype=np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    return int(
        lib.lcs_len(
            a.ctypes.data_as(i64p), ctypes.c_int64(a.shape[0]),
            b.ctypes.data_as(i64p), ctypes.c_int64(b.shape[0]),
        )
    )
