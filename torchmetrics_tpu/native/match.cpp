// COCO greedy detection<->ground-truth matcher, one call per (image, class).
//
// Replaces the per-(image, class, area, threshold) Python loops in
// detection/mean_ap.py (reference semantics: mean_ap.py:510-635): one call
// evaluates ALL area ranges and IoU thresholds, so the Python side makes
// n_nonempty_pairs calls instead of n_pairs * areas * thresholds * dets numpy ops.
//
// Semantics pinned by tests/detection goldens (pycocotools parity):
// - detections arrive score-sorted (stable desc) and truncated to max_det;
// - per area range, ground truths are stably partitioned: in-range first,
//   out-of-range (ignored) last; matching considers only unmatched, non-ignored
//   gts; ties resolve to the lowest partitioned index (numpy argmax semantics);
// - a detection matches the best such gt if IoU > threshold (STRICT inequality);
// - unmatched detections whose own area is out of range are marked ignored.
//
// Threshold convention (deliberate, test-pinned divergence from pycocotools):
// pycocotools seeds its per-detection running best at `min(thr, 1 - 1e-10)`,
// which makes a gt with IoU EXACTLY equal to the threshold matchable
// (effectively `iou >= thr - 1e-10`), and additionally lets "crowd" gts match
// after all real gts were exhausted. This kernel — and the numpy fallback and
// the epoch-level evaluator below, which share the rule — uses strict
// `IoU > thr` and never matches ignored gts. The two conventions differ only
// when an IoU sits exactly ON a threshold (easy to construct with integer
// boxes at thr 0.5, measure-zero for float predictions) or when crowd
// annotations are present (the update API does not ingest `iscrowd`).
// Exact-threshold behaviour is pinned by
// tests/detection/test_native_eval_parity.py::test_exact_threshold_iou_is_not_a_match;
// if pycocotools parity at exact-threshold IoU ever becomes a requirement,
// change BOTH kernels and the numpy fallback together to `best >= thr - 1e-10`.

#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" {

// iou:          (D, G) row-major; rows score-sorted, columns in original gt order
// det_areas:    (D,)
// gt_areas:     (G,)
// thrs:         (T,) IoU thresholds
// ranges:       (A, 2) [lo, hi] area ranges
// det_matches:  (A, T, D) out, zero-initialised by caller
// det_ignore:   (A, T, D) out, zero-initialised
// gt_ignore:    (A, G)    out — ignore flags in the per-area partitioned order
void coco_match(const double* iou, const double* det_areas, const double* gt_areas,
                int64_t D, int64_t G, const double* thrs, int64_t T,
                const double* ranges, int64_t A,
                uint8_t* det_matches, uint8_t* det_ignore, uint8_t* gt_ignore) {
    std::vector<int64_t> gtind(G);
    std::vector<uint8_t> gt_matched(G);
    for (int64_t a = 0; a < A; ++a) {
        const double lo = ranges[2 * a], hi = ranges[2 * a + 1];
        uint8_t* gti = gt_ignore + a * G;
        int64_t k = 0;
        for (int64_t g = 0; g < G; ++g)
            if (!(gt_areas[g] < lo || gt_areas[g] > hi)) gtind[k++] = g;
        const int64_t n_valid = k;
        for (int64_t g = 0; g < G; ++g)
            if (gt_areas[g] < lo || gt_areas[g] > hi) gtind[k++] = g;
        for (int64_t g = 0; g < G; ++g) gti[g] = g >= n_valid;

        for (int64_t t = 0; t < T; ++t) {
            const double thr = thrs[t];
            std::fill(gt_matched.begin(), gt_matched.end(), 0);
            uint8_t* dm = det_matches + (a * T + t) * D;
            uint8_t* di = det_ignore + (a * T + t) * D;
            for (int64_t d = 0; d < D; ++d) {
                const double* row = iou + d * G;
                double best = 0.0;
                int64_t bi = -1;
                for (int64_t g = 0; g < n_valid; ++g) {  // ignored gts never match
                    if (gt_matched[g]) continue;
                    const double v = row[gtind[g]];
                    if (bi < 0 || v > best) { best = v; bi = g; }
                }
                if (bi < 0 || best <= thr) continue;
                dm[d] = 1;
                gt_matched[bi] = 1;
            }
            for (int64_t d = 0; d < D; ++d)
                if (!dm[d] && (det_areas[d] < lo || det_areas[d] > hi)) di[d] = 1;
        }
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Epoch-level COCO bbox evaluation: the WHOLE accumulate stage in one call.
//
// Replaces the per-(class, image) Python driver around coco_match
// (detection/mean_ap.py _calculate/_evaluate_pair/_accumulate, reference
// semantics mean_ap.py:510-844): detections and ground truths arrive as flat
// epoch arrays with image/class-index columns; bucketing, per-image score
// sorting, IoU, greedy matching, and PR-curve accumulation all run here.
// Outputs are the final precision (T,R,C,A,M) and recall (T,C,A,M) tensors,
// pre-filled with -1 by the caller; cells the data never touches stay -1.
//
// Semantics pinned against the numpy path by tests/detection
// (pycocotools-parity fixtures + native-vs-numpy equivalence sweep).

namespace {

struct ImgEval {
    // per-image segment for one (class, image) pair, in ascending image order
    std::vector<double> scores;          // truncated to max_dets[M-1], desc
    std::vector<uint8_t> matches;        // (A, T, D) flat
    std::vector<uint8_t> ignore;         // (A, T, D) flat
    std::vector<int64_t> npig;           // (A,) non-ignored gt count
    int64_t D = 0;
};

inline double box_area_xyxy(const double* b) {
    return (b[2] - b[0]) * (b[3] - b[1]);
}

inline double box_iou_pair(const double* a, const double* b) {
    const double ax = a[2] - a[0], ay = a[3] - a[1];
    const double bx = b[2] - b[0], by = b[3] - b[1];
    const double lx = std::max(a[0], b[0]), ly = std::max(a[1], b[1]);
    const double rx = std::min(a[2], b[2]), ry = std::min(a[3], b[3]);
    const double w = std::max(rx - lx, 0.0), h = std::max(ry - ly, 0.0);
    const double inter = w * h;
    const double uni = ax * ay + bx * by - inter;
    return inter / (uni == 0.0 ? 1.0 : uni);
}

}  // namespace

extern "C" {

void coco_eval_bbox(const double* det_boxes, const double* det_scores,
                    const int64_t* det_img, const int64_t* det_cls, int64_t Nd,
                    const double* gt_boxes, const int64_t* gt_img,
                    const int64_t* gt_cls, int64_t Ng,
                    int64_t n_img, int64_t n_cls,
                    const double* iou_thrs, int64_t T,
                    const double* rec_thrs, int64_t R,
                    const double* ranges, int64_t A,
                    const int64_t* max_dets, int64_t M,
                    double* precision, double* recall) {
    const double EPS = 2.220446049250313e-16;  // np.finfo(float64).eps
    const int64_t max_det_cap = M ? max_dets[M - 1] : 0;

    // counting-sort det/gt indices into (class, image) buckets
    auto bucket = [n_img](const int64_t* cls, const int64_t* img, int64_t N,
                          int64_t n_cls_) {
        std::vector<int64_t> offs(n_cls_ * n_img + 1, 0), out(N);
        for (int64_t i = 0; i < N; ++i) ++offs[cls[i] * n_img + img[i] + 1];
        for (size_t k = 1; k < offs.size(); ++k) offs[k] += offs[k - 1];
        std::vector<int64_t> cur(offs.begin(), offs.end() - 1);
        for (int64_t i = 0; i < N; ++i) out[cur[cls[i] * n_img + img[i]]++] = i;
        return std::make_pair(std::move(offs), std::move(out));
    };
    auto [d_offs, d_idx] = bucket(det_cls, det_img, Nd, n_cls);
    auto [g_offs, g_idx] = bucket(gt_cls, gt_img, Ng, n_cls);

    std::vector<int64_t> order, gtind;
    std::vector<double> iou;
    std::vector<uint8_t> gt_matched;

    for (int64_t c = 0; c < n_cls; ++c) {
        std::vector<ImgEval> evals;
        for (int64_t im = 0; im < n_img; ++im) {
            const int64_t d0 = d_offs[c * n_img + im], d1 = d_offs[c * n_img + im + 1];
            const int64_t g0 = g_offs[c * n_img + im], g1 = g_offs[c * n_img + im + 1];
            const int64_t nD_all = d1 - d0, G = g1 - g0;
            if (nD_all == 0 && G == 0) continue;

            // score sort (stable desc) + truncation to the largest max-det
            order.resize(nD_all);
            for (int64_t i = 0; i < nD_all; ++i) order[i] = d_idx[d0 + i];
            std::stable_sort(order.begin(), order.end(), [&](int64_t x, int64_t y) {
                return det_scores[x] > det_scores[y];
            });
            const int64_t D = std::min<int64_t>(nD_all, max_det_cap);

            ImgEval ev;
            ev.D = D;
            ev.scores.resize(D);
            for (int64_t i = 0; i < D; ++i) ev.scores[i] = det_scores[order[i]];
            ev.matches.assign(A * T * D, 0);
            ev.ignore.assign(A * T * D, 0);
            ev.npig.assign(A, 0);

            iou.resize(D * G);
            for (int64_t i = 0; i < D; ++i)
                for (int64_t g = 0; g < G; ++g)
                    iou[i * G + g] =
                        box_iou_pair(det_boxes + order[i] * 4, gt_boxes + g_idx[g0 + g] * 4);

            gtind.resize(G);
            gt_matched.resize(G);
            for (int64_t a = 0; a < A; ++a) {
                const double lo = ranges[2 * a], hi = ranges[2 * a + 1];
                // stable partition: in-range gts first (match.cpp coco_match order)
                int64_t k = 0;
                for (int64_t g = 0; g < G; ++g) {
                    const double ar = box_area_xyxy(gt_boxes + g_idx[g0 + g] * 4);
                    if (!(ar < lo || ar > hi)) gtind[k++] = g;
                }
                const int64_t n_valid = k;
                for (int64_t g = 0; g < G; ++g) {
                    const double ar = box_area_xyxy(gt_boxes + g_idx[g0 + g] * 4);
                    if (ar < lo || ar > hi) gtind[k++] = g;
                }
                ev.npig[a] = n_valid;

                for (int64_t t = 0; t < T; ++t) {
                    const double thr = iou_thrs[t];
                    std::fill(gt_matched.begin(), gt_matched.begin() + G, 0);
                    uint8_t* dm = ev.matches.data() + (a * T + t) * D;
                    uint8_t* di = ev.ignore.data() + (a * T + t) * D;
                    for (int64_t d = 0; d < D; ++d) {
                        const double* row = iou.data() + d * G;
                        double best = 0.0;
                        int64_t bi = -1;
                        for (int64_t g = 0; g < n_valid; ++g) {
                            if (gt_matched[g]) continue;
                            const double v = row[gtind[g]];
                            if (bi < 0 || v > best) { best = v; bi = g; }
                        }
                        if (bi < 0 || best <= thr) continue;
                        dm[d] = 1;
                        gt_matched[bi] = 1;
                    }
                    for (int64_t d = 0; d < D; ++d) {
                        if (dm[d]) continue;
                        const double ar = box_area_xyxy(det_boxes + order[d] * 4);
                        if (ar < lo || ar > hi) di[d] = 1;
                    }
                }
            }
            evals.push_back(std::move(ev));
        }
        if (evals.empty()) continue;

        // accumulate per (area, max_det): concatenate per-image segments
        // (each truncated to max_det), global stable desc sort, PR curve
        std::vector<double> cat_scores;
        std::vector<int64_t> seg_img, seg_pos, sidx;
        std::vector<double> tp_cum, fp_cum, rc, pr;
        for (int64_t a = 0; a < A; ++a) {
            int64_t npig = 0;
            for (const auto& ev : evals) npig += ev.npig[a];
            if (npig == 0) continue;
            for (int64_t m = 0; m < M; ++m) {
                const int64_t md = max_dets[m];
                cat_scores.clear(); seg_img.clear(); seg_pos.clear();
                for (size_t e = 0; e < evals.size(); ++e) {
                    const int64_t take = std::min(evals[e].D, md);
                    for (int64_t i = 0; i < take; ++i) {
                        cat_scores.push_back(evals[e].scores[i]);
                        seg_img.push_back(static_cast<int64_t>(e));
                        seg_pos.push_back(i);
                    }
                }
                const int64_t nd = static_cast<int64_t>(cat_scores.size());
                sidx.resize(nd);
                for (int64_t i = 0; i < nd; ++i) sidx[i] = i;
                std::stable_sort(sidx.begin(), sidx.end(), [&](int64_t x, int64_t y) {
                    return cat_scores[x] > cat_scores[y];
                });

                for (int64_t t = 0; t < T; ++t) {
                    tp_cum.resize(nd); fp_cum.resize(nd);
                    rc.resize(nd); pr.resize(nd);
                    double tp = 0, fp = 0;
                    for (int64_t i = 0; i < nd; ++i) {
                        const auto& ev = evals[seg_img[sidx[i]]];
                        const int64_t pos = seg_pos[sidx[i]];
                        const uint8_t mt = ev.matches[(a * T + t) * ev.D + pos];
                        const uint8_t ig = ev.ignore[(a * T + t) * ev.D + pos];
                        tp += (mt && !ig);
                        fp += (!mt && !ig);
                        tp_cum[i] = tp; fp_cum[i] = fp;
                        rc[i] = tp / npig;
                        pr[i] = tp / (fp + tp + EPS);
                    }
                    // recall cell: (t, c, a, m) in (T, C, A, M)
                    recall[((t * n_cls + c) * A + a) * M + m] = nd ? rc[nd - 1] : 0.0;
                    // monotone envelope (reverse cummax)
                    for (int64_t i = nd - 2; i >= 0; --i) pr[i] = std::max(pr[i], pr[i + 1]);
                    // searchsorted(rc, rec_thrs, left) then fill until first
                    // out-of-range index (numpy argmax-of-max semantics)
                    int64_t j = 0;
                    for (int64_t r = 0; r < R; ++r) {
                        while (j < nd && rc[j] < rec_thrs[r]) ++j;
                        double* cell = precision + ((((int64_t)t * R + r) * n_cls + c) * A + a) * M + m;
                        *cell = (j < nd) ? pr[j] : 0.0;
                    }
                }
            }
        }
    }
}

}  // extern "C"

extern "C" {

// Longest-common-subsequence length over int token ids (two-row DP).
// Replaces the pure-Python table in functional/text/rouge.py _lcs for ROUGE-L,
// which only needs the length (ROUGE-Lsum backtracks and keeps the table).
int64_t lcs_len(const int64_t* a, int64_t na, const int64_t* b, int64_t nb) {
    if (na <= 0 || nb <= 0) return 0;
    std::vector<int64_t> prev(nb + 1, 0), cur(nb + 1, 0);
    for (int64_t i = 1; i <= na; ++i) {
        const int64_t ai = a[i - 1];
        for (int64_t j = 1; j <= nb; ++j) {
            cur[j] = (ai == b[j - 1]) ? prev[j - 1] + 1
                                      : std::max(prev[j], cur[j - 1]);
        }
        std::swap(prev, cur);
    }
    return prev[nb];
}

}  // extern "C"
