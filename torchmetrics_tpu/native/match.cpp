// COCO greedy detection<->ground-truth matcher, one call per (image, class).
//
// Replaces the per-(image, class, area, threshold) Python loops in
// detection/mean_ap.py (reference semantics: mean_ap.py:510-635): one call
// evaluates ALL area ranges and IoU thresholds, so the Python side makes
// n_nonempty_pairs calls instead of n_pairs * areas * thresholds * dets numpy ops.
//
// Semantics pinned by tests/detection goldens (pycocotools parity):
// - detections arrive score-sorted (stable desc) and truncated to max_det;
// - per area range, ground truths are stably partitioned: in-range first,
//   out-of-range (ignored) last; matching considers only unmatched, non-ignored
//   gts; ties resolve to the lowest partitioned index (numpy argmax semantics);
// - a detection matches the best such gt if IoU > threshold (strict);
// - unmatched detections whose own area is out of range are marked ignored.

#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" {

// iou:          (D, G) row-major; rows score-sorted, columns in original gt order
// det_areas:    (D,)
// gt_areas:     (G,)
// thrs:         (T,) IoU thresholds
// ranges:       (A, 2) [lo, hi] area ranges
// det_matches:  (A, T, D) out, zero-initialised by caller
// det_ignore:   (A, T, D) out, zero-initialised
// gt_ignore:    (A, G)    out — ignore flags in the per-area partitioned order
void coco_match(const double* iou, const double* det_areas, const double* gt_areas,
                int64_t D, int64_t G, const double* thrs, int64_t T,
                const double* ranges, int64_t A,
                uint8_t* det_matches, uint8_t* det_ignore, uint8_t* gt_ignore) {
    std::vector<int64_t> gtind(G);
    std::vector<uint8_t> gt_matched(G);
    for (int64_t a = 0; a < A; ++a) {
        const double lo = ranges[2 * a], hi = ranges[2 * a + 1];
        uint8_t* gti = gt_ignore + a * G;
        int64_t k = 0;
        for (int64_t g = 0; g < G; ++g)
            if (!(gt_areas[g] < lo || gt_areas[g] > hi)) gtind[k++] = g;
        const int64_t n_valid = k;
        for (int64_t g = 0; g < G; ++g)
            if (gt_areas[g] < lo || gt_areas[g] > hi) gtind[k++] = g;
        for (int64_t g = 0; g < G; ++g) gti[g] = g >= n_valid;

        for (int64_t t = 0; t < T; ++t) {
            const double thr = thrs[t];
            std::fill(gt_matched.begin(), gt_matched.end(), 0);
            uint8_t* dm = det_matches + (a * T + t) * D;
            uint8_t* di = det_ignore + (a * T + t) * D;
            for (int64_t d = 0; d < D; ++d) {
                const double* row = iou + d * G;
                double best = 0.0;
                int64_t bi = -1;
                for (int64_t g = 0; g < n_valid; ++g) {  // ignored gts never match
                    if (gt_matched[g]) continue;
                    const double v = row[gtind[g]];
                    if (bi < 0 || v > best) { best = v; bi = g; }
                }
                if (bi < 0 || best <= thr) continue;
                dm[d] = 1;
                gt_matched[bi] = 1;
            }
            for (int64_t d = 0; d < D; ++d)
                if (!dm[d] && (det_areas[d] < lo || det_areas[d] > hi)) di[d] = 1;
        }
    }
}

}  // extern "C"
