"""Native host-side kernels (C++, ctypes-bound).

The reference's only native surface is third-party (pycocotools' C RLE mask ops,
ATen); this package holds the first-party equivalents the TPU build needs on host
(SURVEY §2.12). Kernels compile lazily with the baked-in ``g++`` into the package's
``_build`` directory; every entry point has a pure-numpy fallback so the framework
works even without a toolchain.
"""

from torchmetrics_tpu.native.rle_mask import (
    coco_eval_bbox,
    coco_eval_bbox_available,
    coco_match,
    native_available,
    rle_area,
    rle_decode,
    rle_encode,
    rle_iou,
)

__all__ = [
    "coco_eval_bbox",
    "coco_eval_bbox_available",
    "coco_match",
    "native_available",
    "rle_area",
    "rle_decode",
    "rle_encode",
    "rle_iou",
]
