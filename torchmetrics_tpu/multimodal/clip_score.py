"""Modular CLIPScore (reference ``multimodal/clip_score.py:28-158``)."""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.multimodal.clip_score import (
    _DEFAULT_MODEL,
    _clip_score_update,
    _get_model_and_processor,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class CLIPScore(Metric):
    """Streaming text-image similarity with score/n_samples sum states."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 100.0

    score: Array
    n_samples: Array

    def __init__(
        self,
        model_name_or_path: str = _DEFAULT_MODEL,
        embed_fn: Optional[Callable[[List[Array], List[str]], Tuple[Array, Array]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.embed_fn = embed_fn
        if embed_fn is None:
            self.model, self.processor = _get_model_and_processor(model_name_or_path)
        else:
            self.model = self.processor = None
        self.add_state("score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, images: Union[Array, List[Array]], text: Union[str, List[str]]) -> None:
        """Fold one batch of image/caption pairs into the running score."""
        score, n_samples = _clip_score_update(images, text, self.model, self.processor, self.embed_fn)
        self.score = self.score + score.sum(0)
        self.n_samples = self.n_samples + n_samples

    def compute(self) -> Array:
        """Average CLIPScore clamped at zero."""
        return jnp.maximum(self.score / self.n_samples, jnp.asarray(0.0))

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
