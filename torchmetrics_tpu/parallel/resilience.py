"""Bounded collectives — deadline, retry/backoff, and typed fault taxonomy.

The reference library trusts ``torch.distributed`` absolutely: a hung or dead
rank wedges every epoch-end ``gather_all_tensors`` forever. This module bounds
every host collective the package issues (the packed-sync backbone in
``parallel/packing.py`` AND the eager per-tensor path in ``parallel/sync.py``)
with an explicit policy:

- **Deadline** (``TORCHMETRICS_TPU_SYNC_DEADLINE_MS`` / ``resilience_context``):
  the collective runs on a watchdog thread; if it has not returned within the
  deadline the caller gets a :class:`CollectiveTimeoutError` instead of an
  indefinite hang. (The abandoned worker thread is a daemon — the underlying
  collective cannot be cancelled, only *escaped*; document-level honesty, the
  same trade every collective-timeout implementation makes.) No deadline
  configured = the wrapper adds zero machinery to the call.
- **Bounded retry + exponential backoff** (``TORCHMETRICS_TPU_SYNC_RETRIES`` /
  ``TORCHMETRICS_TPU_SYNC_BACKOFF_MS``): *retryable* failures (timeout,
  payload corruption — transient by nature) re-enter the collective up to the
  bound, sleeping ``backoff_ms * 2**attempt`` between attempts; each retry is
  a counted ``sync.retry`` flight-recorder fact.
- **Classification**: every failure surfaces as a typed
  :class:`SyncFaultError` subclass — :class:`CollectiveTimeoutError`,
  :class:`RankUnreachableError` (not retryable: a dead rank does not come back
  because we asked again; degraded-mode folding in ``engine/epoch.py`` is the
  remedy), :class:`PayloadCorruptError` (CRC mismatch, retryable).
- **Payload integrity** (``verify_payload``): the wrapper fingerprints the
  local buffer (crc32 over its raw bytes — the same digest family the PR-4
  divergence audit stamps into the metadata gather) and verifies the gathered
  result echoes it bit-exactly at this rank's row. That catches loopback/
  transport corruption of the local shard; *cross-rank* value integrity is the
  opt-in audit's job (it carries every rank's state CRCs in the metadata
  exchange).

Fault injection (``parallel/faults.py``) plugs in at exactly this boundary, so
every recovery path above is exercisable deterministically in tests and bench
chaos scenarios without a real multi-host world.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Callable, Dict, Generator, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "BACKOFF_ENV_VAR",
    "DEADLINE_ENV_VAR",
    "DEGRADED_ENV_VAR",
    "RETRIES_ENV_VAR",
    "CollectiveTimeoutError",
    "PayloadCorruptError",
    "RankUnreachableError",
    "ResiliencePolicy",
    "SyncFaultError",
    "bounded_collective",
    "bounded_pull",
    "consume_straggler_hint",
    "current_policy",
    "last_straggler_rank",
    "note_straggler",
    "reset_resilience",
    "resilience_context",
    "resilience_snapshot",
]

#: hard wall-clock bound (ms) on one host collective; unset/0 = unbounded
DEADLINE_ENV_VAR = "TORCHMETRICS_TPU_SYNC_DEADLINE_MS"
#: bounded retries for retryable faults (timeout / corrupt payload)
RETRIES_ENV_VAR = "TORCHMETRICS_TPU_SYNC_RETRIES"
#: base backoff (ms) between retries; attempt k sleeps base * 2**k
BACKOFF_ENV_VAR = "TORCHMETRICS_TPU_SYNC_BACKOFF_MS"
#: "0" forbids degraded-mode folding over surviving membership (default allowed)
DEGRADED_ENV_VAR = "TORCHMETRICS_TPU_DEGRADED"

DEFAULT_RETRIES = 2
DEFAULT_BACKOFF_MS = 25.0


class SyncFaultError(RuntimeError):
    """A host collective failed in a *classified* way instead of hanging.

    ``label`` is the collective's buffer key (``"reduce:int32"``, ``"meta"``,
    ``"eager:state"`` …); ``rank`` names the culprit when one is known (the
    degraded-mode re-plan in ``engine/epoch.py`` folds over the survivors);
    ``attempts`` is how many tries the bounded-retry policy spent.
    """

    retryable = False

    def __init__(self, message: str, label: str = "", rank: Optional[int] = None, attempts: int = 1):
        super().__init__(message)
        self.label = label
        self.rank = rank
        self.attempts = attempts


class CollectiveTimeoutError(SyncFaultError):
    """The collective exceeded the configured deadline.

    Retryable as a class — a *planted* deadline expiry (fault harness, or a
    delayed rank classified before the collective was issued) is transient by
    nature. A timeout that escaped an **in-flight** collective via the
    watchdog is marked ``retryable = False`` per instance (and
    ``in_flight = True``): the abandoned worker may still complete its
    collective later, so re-entering would desequence this rank's collective
    stream against its peers — silent corruption, strictly worse than the
    typed error. Recovery for that case is the degraded re-plan or the
    operator's restart policy, both explicit and observable.
    """

    retryable = True
    in_flight = False


class RankUnreachableError(SyncFaultError):
    """A rank is gone from the world (NOT retryable — degrade or fail)."""

    retryable = False


class PayloadCorruptError(SyncFaultError):
    """The gathered payload failed its CRC integrity check (retryable)."""

    retryable = True


class ResiliencePolicy:
    """Resolved knob set governing one collective call."""

    __slots__ = ("deadline_ms", "retries", "backoff_ms", "degraded", "verify_payload")

    def __init__(
        self,
        deadline_ms: Optional[float] = None,
        retries: int = DEFAULT_RETRIES,
        backoff_ms: float = DEFAULT_BACKOFF_MS,
        degraded: bool = True,
        verify_payload: bool = False,
    ) -> None:
        self.deadline_ms = None if not deadline_ms else float(deadline_ms)
        self.retries = max(0, int(retries))
        self.backoff_ms = max(0.0, float(backoff_ms))
        self.degraded = bool(degraded)
        self.verify_payload = bool(verify_payload)


_POLICY_VAR: "ContextVar[Optional[ResiliencePolicy]]" = ContextVar("tm_tpu_resilience", default=None)


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def current_policy() -> ResiliencePolicy:
    """The policy in force: an active ``resilience_context`` scope, else env."""
    scoped = _POLICY_VAR.get()
    if scoped is not None:
        return scoped
    retries = _env_float(RETRIES_ENV_VAR)
    backoff = _env_float(BACKOFF_ENV_VAR)
    return ResiliencePolicy(
        deadline_ms=_env_float(DEADLINE_ENV_VAR),
        retries=DEFAULT_RETRIES if retries is None else int(retries),
        backoff_ms=DEFAULT_BACKOFF_MS if backoff is None else backoff,
        degraded=os.environ.get(DEGRADED_ENV_VAR, "").strip() != "0",
    )


@contextmanager
def resilience_context(
    deadline_ms: Optional[float] = None,
    retries: int = DEFAULT_RETRIES,
    backoff_ms: float = DEFAULT_BACKOFF_MS,
    degraded: bool = True,
    verify_payload: bool = False,
) -> Generator[ResiliencePolicy, None, None]:
    """Scoped collective-resilience policy (tests, benches, serving loops)."""
    policy = ResiliencePolicy(deadline_ms, retries, backoff_ms, degraded, verify_payload)
    token = _POLICY_VAR.set(policy)
    try:
        yield policy
    finally:
        _POLICY_VAR.reset(token)


# ------------------------------------------------------------------ counters

# module-level fact surface (reset in the reset_engine_stats lockstep); the
# epoch engine diffs total_retries() around an exchange to feed EngineStats
_COUNTS: Dict[str, int] = {}

#: the last straggler rank the packed-sync timeline named (diag/timeline.py);
#: a timeout that does not know its culprit falls back to this attribution
_last_straggler: Optional[int] = None


def _count(key: str) -> None:
    _COUNTS[key] = _COUNTS.get(key, 0) + 1


def total_retries() -> int:
    return _COUNTS.get("retries", 0)


def note_straggler(rank: int) -> None:
    """Remember the rank the straggler detector last named (degraded-fold hint)."""
    global _last_straggler
    _last_straggler = int(rank)


def last_straggler_rank() -> Optional[int]:
    return _last_straggler


def consume_straggler_hint() -> Optional[int]:
    """Read AND clear the straggler hint — each attribution is spent once.

    The degraded re-plan blames a rank only on fresh evidence: either the
    fault itself names one, or the most recent flagged straggler does. A
    consumed (or never-set) hint means an anonymous fault propagates as its
    typed error instead of silently excluding a possibly-healthy rank's data
    on stale attribution — fail loud beats fold wrong.
    """
    global _last_straggler
    rank, _last_straggler = _last_straggler, None
    return rank


def resilience_snapshot() -> Dict[str, Any]:
    """Counters + policy view (deterministically sorted, byte-stable JSON)."""
    policy = current_policy()
    return {
        "counts": {k: _COUNTS[k] for k in sorted(_COUNTS)},
        "deadline_ms": policy.deadline_ms,
        "retries": policy.retries,
        "backoff_ms": policy.backoff_ms,
        "degraded": policy.degraded,
        "last_straggler_rank": _last_straggler,
    }


def reset_resilience() -> None:
    """Zero the fault/retry counters (``reset_engine_stats`` lockstep); the
    policy knobs are configuration, not measurement, and survive."""
    global _last_straggler
    _COUNTS.clear()
    _last_straggler = None


# ------------------------------------------------------------------ the wrapper


# tmlint: boundary(sync-fault) — CRC echo verification materializes the local
# payload row; opt-in (verify_payload) and part of the declared fault machinery
def _payload_crc(payload: Any) -> Optional[int]:
    """crc32 over the payload's raw bytes; None when it has no buffer view."""
    try:
        arr = np.asarray(payload)
        return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF
    except Exception:  # noqa: BLE001 — non-array payloads just skip verification
        return None


def _local_rank() -> int:
    import jax

    try:
        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — un-initialized backend reads as rank 0
        return 0


def _call_with_deadline(call: Callable[[], Any], deadline_ms: float, label: str, attempts: int) -> Any:
    """Run ``call`` on a watchdog thread; escape with a typed timeout.

    The worker is a daemon: a genuinely hung collective cannot be cancelled
    from the host side, so the caller *escapes* (typed error, degraded-fold
    option) while the dead thread is abandoned — strictly better than the
    reference behavior (the whole process wedges forever).
    """
    box: Dict[str, Any] = {}

    def run() -> None:
        try:
            box["out"] = call()
        except BaseException as exc:  # noqa: BLE001 — re-raised on the caller thread
            box["err"] = exc

    worker = threading.Thread(target=run, daemon=True, name=f"tm-collective-{label}")
    worker.start()
    worker.join(deadline_ms / 1e3)
    if worker.is_alive():
        err = CollectiveTimeoutError(
            f"collective {label!r} exceeded the {deadline_ms:g} ms deadline"
            f" (attempt {attempts}); the epoch would have hung without it."
            " The in-flight collective was abandoned, so this error is not"
            " retried — re-entering could desequence the collective stream"
            " if the abandoned call later completes",
            label=label,
            rank=None,  # culprit attribution is the degraded re-plan's job
            attempts=attempts,
        )
        err.retryable = False
        err.in_flight = True
        raise err
    if "err" in box:
        raise box["err"]
    return box["out"]


def bounded_collective(
    call: Callable[[], Any],
    label: str = "",
    payload: Any = None,
    members: Optional[Sequence[int]] = None,
) -> Any:
    """Run one host collective under the active resilience policy.

    ``call`` performs the raw collective (re-invoked on retry); ``payload`` is
    the local buffer (CRC echo verification); ``members`` is the plan's live
    membership — the fault-injection harness consults it so a rank excluded by
    a degraded re-plan no longer fires its fault (the harness's model of a
    reformed communicator).

    Raises a typed :class:`SyncFaultError` subclass when the policy's bounds
    are exhausted — never hangs past a configured deadline, never retries
    unboundedly, never mislabels a failure as a generic crash.
    """
    from torchmetrics_tpu.diag import trace as _diag
    from torchmetrics_tpu.parallel import faults as _faults

    policy = current_policy()
    local_crc = _payload_crc(payload) if policy.verify_payload else None
    attempt = 0
    while True:
        attempts = attempt + 1
        try:
            _faults.apply_before(label, members, policy.deadline_ms, attempts)
            if policy.deadline_ms is not None:
                out = _call_with_deadline(call, policy.deadline_ms, label, attempts)
            else:
                out = call()
            out = _faults.apply_after(label, members, out)
            if local_crc is not None:
                rank = _local_rank()
                # tmlint: disable=TM101 — `out` is the gathered host result
                # (the collective already crossed at its sanctioned boundary)
                got = np.asarray(out)
                if rank < got.shape[0]:
                    echo_crc = zlib.crc32(np.ascontiguousarray(got[rank]).tobytes()) & 0xFFFFFFFF
                    if echo_crc != local_crc:
                        raise PayloadCorruptError(
                            f"collective {label!r}: gathered row {rank} does not echo the"
                            f" local payload (crc {echo_crc:#010x} != {local_crc:#010x},"
                            f" attempt {attempts})",
                            label=label,
                            rank=rank,
                            attempts=attempts,
                        )
            return out
        except SyncFaultError as exc:
            exc.attempts = attempts
            _count(f"fault:{type(exc).__name__}")
            if not exc.retryable or attempt >= policy.retries:
                _diag.record(
                    "sync.fault", "", label=label, error=type(exc).__name__,
                    rank=exc.rank, attempts=attempts, retryable=exc.retryable,
                )
                raise
            _count("retries")
            _diag.record(
                "sync.retry", "", label=label, error=type(exc).__name__,
                rank=exc.rank, attempt=attempts, backoff_ms=policy.backoff_ms * (2 ** attempt),
            )
            if policy.backoff_ms:
                time.sleep(policy.backoff_ms * (2 ** attempt) / 1e3)
            attempt += 1


def bounded_pull(
    fetch: Callable[[], Any],
    label: str = "",
    rank: Optional[int] = None,
    members: Optional[Sequence[int]] = None,
) -> Any:
    """Run one point-to-point fetch (a federation or fleet pod pull) under the policy.

    The aggregation-tier sibling of :func:`bounded_collective`: the same
    deadline watchdog, bounded retry/backoff, typed-fault classification, and
    fault-injection hook (``parallel/faults.py`` plants at this boundary via
    the ``label``/``members`` contract, so pod-churn chaos rides the
    production path). Both aggregation planes pull through here — state
    envelopes on ``federation-pull:<pod>`` labels (``serve/federation.py``)
    and telemetry envelopes on ``fleet-pull:<pod>`` labels
    (``serve/fleet.py``). Two deliberate differences:

    - A **pull is idempotent** — it reads a pod's snapshot endpoint, it does
      not participate in an ordered collective stream — so a deadline expiry
      that abandoned an in-flight fetch IS retried (``bounded_collective``
      must not re-enter an abandoned collective; a re-issued GET is harmless).
    - Untyped transport failures (socket errors, HTTP failures) classify as
      :class:`RankUnreachableError` naming ``rank`` — not retryable: the
      remedy is the aggregator's degraded fold over the reachable pods, the
      exact recovery shape the degraded re-plan gives a dead rank.
    """
    from torchmetrics_tpu.diag import trace as _diag
    from torchmetrics_tpu.parallel import faults as _faults

    policy = current_policy()
    attempt = 0
    while True:
        attempts = attempt + 1
        try:
            _faults.apply_before(label, members, policy.deadline_ms, attempts)
            try:
                if policy.deadline_ms is not None:
                    out = _call_with_deadline(fetch, policy.deadline_ms, label, attempts)
                else:
                    out = fetch()
            except SyncFaultError:
                raise
            except Exception as exc:  # noqa: BLE001 — transport failure, classified below
                raise RankUnreachableError(
                    f"pull {label!r} failed to reach its pod"
                    f" ({type(exc).__name__}: {exc}, attempt {attempts})",
                    label=label,
                    rank=rank,
                    attempts=attempts,
                ) from exc
            return _faults.apply_after(label, members, out)
        except SyncFaultError as exc:
            exc.attempts = attempts
            if isinstance(exc, CollectiveTimeoutError):
                # an abandoned in-flight GET is safe to re-issue (idempotent
                # read) — undo the watchdog's no-retry marking for pulls
                exc.retryable = exc.retryable or exc.in_flight
            if exc.rank is None:
                exc.rank = rank
            _count(f"fault:{type(exc).__name__}")
            if not exc.retryable or attempt >= policy.retries:
                _diag.record(
                    "sync.fault", "", label=label, error=type(exc).__name__,
                    rank=exc.rank, attempts=attempts, retryable=exc.retryable,
                )
                raise
            _count("retries")
            _diag.record(
                "sync.retry", "", label=label, error=type(exc).__name__,
                rank=exc.rank, attempt=attempts, backoff_ms=policy.backoff_ms * (2 ** attempt),
            )
            if policy.backoff_ms:
                time.sleep(policy.backoff_ms * (2 ** attempt) / 1e3)
            attempt += 1
