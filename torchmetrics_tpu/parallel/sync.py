"""Cross-chip state synchronization — the communication backend.

Capability parity: reference ``src/torchmetrics/utilities/distributed.py:90-146`` +
``metric.py:386-416``, whose single primitive is ``torch.distributed.all_gather`` over
NCCL/gloo process groups, with ragged tensors handled by gather-shapes → pad → gather →
trim.

TPU-native design (three sync modes, §5.8 of SURVEY):

1. **In-graph mesh-axis collectives** (`axis_gather`/`axis_sum`/...): for metric states
   living inside ``shard_map``/``pmap`` over a ``jax.sharding.Mesh`` — lowers to XLA
   ``all-gather``/``all-reduce`` riding the ICI. Sum-reducible states use ``psum``
   (one all-reduce) instead of the reference's gather-then-sum (world-size bandwidth).
2. **Host/process collectives** (`gather_all_tensors`): for multi-process (multi-host
   pod) programs outside jit — built on ``jax.experimental.multihost_utils``. The
   ``process_group`` concept generalizes to a sub-mesh of processes.
3. **Global-array mode**: with pjit + globally-sharded inputs, XLA inserts the
   collectives automatically — no explicit sync is needed; ``distributed_available``
   then reports False and sync is a no-op, which is correct by construction.

Pluggable exactly like the reference: ``Metric(dist_sync_fn=...)`` receives any
callable ``(tensor, group) -> list[tensor]``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

__all__ = [
    "jit_distributed_available",
    "gather_all_tensors",
    "axis_gather",
    "axis_sum",
    "axis_mean",
    "axis_max",
    "axis_min",
    "EvalMesh",
]


def jit_distributed_available() -> bool:
    """Is there more than one process? (reference ``metric.py:41-43``)."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


# --------------------------------------------------------------------------------------
# Mode 1 — in-graph collectives over a named mesh axis (ICI path)
# --------------------------------------------------------------------------------------

def axis_gather(x: Array, axis_name: str) -> Array:
    """``all_gather`` over a mesh axis; result has a new leading world dim."""
    return lax.all_gather(x, axis_name)


def axis_sum(x: Array, axis_name: str) -> Array:
    """``psum`` over a mesh axis — the sum-reducible state sync primitive."""
    return lax.psum(x, axis_name)


def axis_mean(x: Array, axis_name: str) -> Array:
    """``pmean`` over a mesh axis."""
    return lax.pmean(x, axis_name)


def axis_max(x: Array, axis_name: str) -> Array:
    """``pmax`` over a mesh axis."""
    return lax.pmax(x, axis_name)


def axis_min(x: Array, axis_name: str) -> Array:
    """``pmin`` over a mesh axis."""
    return lax.pmin(x, axis_name)


# --------------------------------------------------------------------------------------
# Mode 2 — host-level process collectives (DCN / multi-host path)
# --------------------------------------------------------------------------------------

def _bounded_allgather(x: Any, label: str) -> Any:
    """One eager-path ``process_allgather`` under the resilience policy.

    The eager (non-engine) sync path — every fallback counted by
    ``EngineStats.fallback`` lands here — must not be able to deadlock either:
    the same deadline/retry/typed-error policy that bounds the packed backbone
    (``parallel/resilience.py``) bounds these collectives, and the fault
    harness (``parallel/faults.py``) can plant at them via ``eager:*`` labels.
    """
    from jax.experimental import multihost_utils

    from torchmetrics_tpu.parallel.resilience import bounded_collective

    return bounded_collective(
        lambda: multihost_utils.process_allgather(x, tiled=False), label=label, payload=x
    )


def _simple_gather_all_tensors(result: Array, group: Any, world_size: int) -> List[Array]:
    """Equal-shape gather (reference ``distributed.py:90-94``)."""
    # process_allgather returns host numpy — convert so downstream reductions see
    # device arrays like every other sync mode
    gathered = _bounded_allgather(result, "eager:state")
    return [jnp.asarray(gathered[i]) for i in range(world_size)]


def gather_all_tensors(
    result: Array, group: Optional[Any] = None, assume_equal_shapes: bool = False
) -> List[Array]:
    """Gather one (possibly ragged along dim 0) array from every process.

    Mirrors reference ``utilities/distributed.py:96-146``: gather shapes first; if all
    equal do the plain gather; otherwise pad every local tensor to the elementwise max
    shape, gather, and trim each result back to its true shape. Works on any pytree
    leaf; assumes equal rank across processes (as the reference does).

    ``group`` (the reference's ``process_group``) may be a sequence of process indices
    defining a sub-world: the gather still rides the full-world collective (DCN
    bandwidth is the same), but only the group's members are returned, so reductions
    see exactly the sub-world state.

    ``assume_equal_shapes`` skips the shape-metadata exchange entirely when the
    caller can prove the shape is rank-invariant (e.g. a ``dist_sync_fn``
    wrapper syncing only fixed-shape states; the packed-sync plan reaches the
    same effect through its own rank-invariance analysis in
    ``parallel/packing.py``). Scalars skip it unconditionally: a 0-d array has
    exactly one possible shape, so the old path's metadata gather bought
    nothing.
    """
    if not jit_distributed_available():
        return [result]
    world_size = jax.process_count()
    members = list(range(world_size)) if group is None else [int(i) for i in group]
    result = jnp.asarray(result)

    if assume_equal_shapes or result.ndim == 0:
        gathered = _simple_gather_all_tensors(result, group, world_size)
        return [gathered[i] for i in members]

    local_shape = jnp.asarray(result.shape, dtype=jnp.int32)
    all_shapes = _bounded_allgather(local_shape, "eager:shape")
    all_shapes = [tuple(int(d) for d in all_shapes[i]) for i in range(world_size)]

    # EVERY process participates in the underlying collective (sub-worlds only
    # filter the results), so both the equal-shape fast path and the pad target
    # must consider ALL ranks — padding to the members' max alone gives a
    # non-member with a larger shape a negative pad, killing it while the members
    # deadlock in the collective (caught by the world-3 sub-group test).
    if all(s == all_shapes[0] for s in all_shapes):
        gathered = _bounded_allgather(result, "eager:state")
        return [jnp.asarray(gathered[i]) for i in members]

    max_shape = tuple(max(s[d] for s in all_shapes) for d in range(result.ndim))
    pad = [(0, m - s) for m, s in zip(max_shape, result.shape)]
    padded = jnp.pad(result, pad)
    gathered = _bounded_allgather(padded, "eager:state")
    out = []
    for i in members:
        slices = tuple(slice(0, d) for d in all_shapes[i])
        out.append(jnp.asarray(gathered[i][slices]))
    return out


# --------------------------------------------------------------------------------------
# Mode 3 helper — single-process multi-device evaluation mesh
# --------------------------------------------------------------------------------------

class EvalMesh:
    """Convenience wrapper producing a 1-D data-parallel mesh over local devices.

    Used by tests and benches to emulate an N-chip pod: 8 virtual CPU devices via
    ``--xla_force_host_platform_device_count=8`` (SURVEY §4 "TPU-build translation").
    """

    def __init__(self, n_devices: Optional[int] = None, axis: str = "data"):
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
        self.axis = axis
        self.mesh = jax.sharding.Mesh(devices, (axis,))

    @property
    def size(self) -> int:
        return self.mesh.devices.size

    def shard_batch(self, x: Array) -> Array:
        """Shard dim 0 of a host array across the mesh."""
        sharding = jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec(self.axis))
        return jax.device_put(x, sharding)

    def replicate(self, x: Array) -> Array:
        sharding = jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec())
        return jax.device_put(x, sharding)
