"""Deterministic fault injection at the collective boundary.

Every recovery path in the fault-tolerance layer — bounded retry, degraded-mode
folding, payload-CRC rejection — is only as trustworthy as its exercise. This
harness plants faults at exactly the boundary the resilience wrapper guards
(:func:`~torchmetrics_tpu.parallel.resilience.bounded_collective`, which every
``all_gather_backbone`` and eager ``gather_all_tensors`` call rides), so tests,
``bench.py`` chaos scenarios, and CI all drive the *production* code path — no
parallel test-only shims.

Design rules:

- **Deterministic and seed-free.** A fault fires on the Nth matching call
  (``after`` skips, ``times`` bounds), never on a random draw — a chaos run is
  reproducible byte-for-byte.
- **Membership-aware.** Rank-scoped faults (``RankDrop``, ``DelayRank``,
  ``CorruptPayload``) consult the live membership the caller passes: a rank
  excluded by a degraded re-plan no longer fires its fault. That is the
  harness's model of a reformed communicator over the survivors — exactly the
  behavior a real elastic runtime exhibits after it evicts a dead rank.
- **Scoped.** ``fault_context(...)`` is a contextvar scope; nothing leaks into
  the process after the ``with`` block.

Fault kinds (all raise/act through the resilience wrapper's classification):

=====================  ======================================================
``CollectiveTimeout``  the matching collective raises
                       :class:`~torchmetrics_tpu.parallel.resilience.
                       CollectiveTimeoutError` (simulating a deadline expiry)
``RankDrop``           a rank is unreachable: matching collectives raise
                       :class:`RankUnreachableError` *while the rank is in the
                       live membership* — persistent by default
``DelayRank``          a rank genuinely sleeps before the collective; when the
                       sleep exceeds the configured deadline the call times
                       out *naming that rank*
``CorruptPayload``     the gathered result's row for ``rank`` is bit-flipped
                       after the collective (transport corruption); the CRC
                       echo check classifies it
=====================  ======================================================
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Generator, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "CollectiveTimeout",
    "CorruptPayload",
    "DelayRank",
    "Fault",
    "RankDrop",
    "active_faults",
    "apply_after",
    "apply_before",
    "fault_context",
]


class Fault:
    """One deterministic injection rule.

    Args:
        label: collective label to match — ``None`` matches any, a trailing
            ``*`` matches by prefix (``"reduce:*"``), otherwise exact.
        rank: the rank this fault models (required for rank-scoped kinds).
        times: matching calls that fire (``None`` = every one; the default 1
            keeps "fires once, recovery retries succeed" the natural shape).
        after: matching calls to skip before the first fire.
    """

    kind = ""
    rank_scoped = False

    def __init__(
        self,
        label: Optional[str] = None,
        rank: Optional[int] = None,
        times: Optional[int] = 1,
        after: int = 0,
    ) -> None:
        if self.rank_scoped and rank is None:
            raise ValueError(f"{type(self).__name__} requires a target rank")
        self.label = label
        self.rank = rank
        self.times = times
        self.after = int(after)
        self.fired = 0
        self._seen = 0

    def _matches(self, label: str) -> bool:
        if self.label is None:
            return True
        if self.label.endswith("*"):
            return label.startswith(self.label[:-1])
        return label == self.label

    def due(self, label: str, members: Optional[Sequence[int]]) -> bool:
        """Consume one matching call; True when this one fires.

        Membership is a *precondition*, not a consumption: a rank-scoped fault
        whose rank has been excluded from the live membership neither fires
        nor counts the call (the reformed communicator no longer talks to it).
        """
        if not self._matches(label):
            return False
        if self.rank_scoped and members is not None and self.rank not in members:
            return False
        self._seen += 1
        if self._seen <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        self.fired += 1
        return True


class CollectiveTimeout(Fault):
    """The matching collective times out (a planted deadline expiry)."""

    kind = "timeout"


class RankDrop(Fault):
    """``rank`` is dead: matching collectives fail while it is in the world.

    Persistent by default (``times=None``) — a dead rank stays dead; recovery
    is the degraded re-plan that removes it from the membership, after which
    this fault's membership precondition stops it firing.
    """

    kind = "rank-drop"
    rank_scoped = True

    def __init__(self, rank: int, label: Optional[str] = None, times: Optional[int] = None, after: int = 0):
        super().__init__(label=label, rank=rank, times=times, after=after)


class DelayRank(Fault):
    """``rank`` arrives late: the call genuinely sleeps ``delay_ms`` first.

    With a deadline configured and ``delay_ms`` past it, the collective times
    out *naming the delayed rank* — the measured-not-forged ethos of the PR-5
    planted-straggler scenarios.
    """

    kind = "delay"
    rank_scoped = True

    def __init__(self, rank: int, delay_ms: float, label: Optional[str] = None, times: Optional[int] = 1, after: int = 0):
        super().__init__(label=label, rank=rank, times=times, after=after)
        self.delay_ms = float(delay_ms)


class CorruptPayload(Fault):
    """Bit-flip the gathered row of ``rank`` after the collective returns."""

    kind = "corrupt"
    rank_scoped = True

    def __init__(self, rank: int, label: Optional[str] = None, times: Optional[int] = 1, after: int = 0):
        super().__init__(label=label, rank=rank, times=times, after=after)


_FAULTS_VAR: "ContextVar[Tuple[Fault, ...]]" = ContextVar("tm_tpu_faults", default=())


@contextmanager
def fault_context(*faults: Fault) -> Generator[Tuple[Fault, ...], None, None]:
    """Scope the given faults over every bounded collective inside the block."""
    for f in faults:
        if not isinstance(f, Fault):
            raise TypeError(f"expected Fault instances, got {type(f).__name__}")
    token = _FAULTS_VAR.set(_FAULTS_VAR.get() + tuple(faults))
    try:
        yield tuple(faults)
    finally:
        _FAULTS_VAR.reset(token)


def active_faults() -> Tuple[Fault, ...]:
    return _FAULTS_VAR.get()


def apply_before(
    label: str,
    members: Optional[Sequence[int]],
    deadline_ms: Optional[float],
    attempt: int,
) -> None:
    """Fire pre-collective faults (timeout / drop / delay) for this call."""
    from torchmetrics_tpu.parallel import resilience as _res

    for fault in _FAULTS_VAR.get():
        if fault.kind == "timeout" and fault.due(label, members):
            raise _res.CollectiveTimeoutError(
                f"planted collective timeout on {label!r} (attempt {attempt})",
                label=label,
                rank=fault.rank,
                attempts=attempt,
            )
        if fault.kind == "rank-drop" and fault.due(label, members):
            raise _res.RankUnreachableError(
                f"planted rank-drop: rank {fault.rank} unreachable in {label!r}",
                label=label,
                rank=fault.rank,
                attempts=attempt,
            )
        if fault.kind == "delay" and fault.due(label, members):
            time.sleep(fault.delay_ms / 1e3)  # the rank is GENUINELY late
            if deadline_ms is not None and fault.delay_ms > deadline_ms:
                raise _res.CollectiveTimeoutError(
                    f"rank {fault.rank} exceeded the {deadline_ms:g} ms deadline on"
                    f" {label!r} (arrived after {fault.delay_ms:g} ms, attempt {attempt})",
                    label=label,
                    rank=fault.rank,
                    attempts=attempt,
                )


# tmlint: boundary(fault-inject) — deliberately materializes the gathered
# payload to corrupt one rank's row; fault injection IS a declared host read
def apply_after(label: str, members: Optional[Sequence[int]], gathered: Any) -> Any:
    """Fire post-collective faults (payload corruption) on the gathered rows."""
    out = gathered
    for fault in _FAULTS_VAR.get():
        if fault.kind != "corrupt" or not fault.due(label, members):
            continue
        arr = np.array(np.asarray(out), copy=True)
        if arr.ndim >= 1 and fault.rank is not None and fault.rank < arr.shape[0]:
            row = np.ascontiguousarray(arr[fault.rank])
            flipped = row.view(np.uint8) ^ np.uint8(0xFF)  # bit-flip every byte
            arr[fault.rank] = flipped.view(row.dtype).reshape(row.shape)
        out = arr
    return out
